"""MeshPropagator: hosts sharded across a device mesh.

The multi-device propagation backend behind `--scheduler=tpu` with
`experimental.tpu_shards > 1`. It is the TPU-native analog of the
reference's scale-out story (worker threads over locked per-host event
queues, src/main/core/worker.rs:597-607 + manager.rs:447-487): hosts are
partitioned into contiguous shards, one device per shard; each round

  1. every host's emitted packets are buffered into its shard's outbox
     (the only `send()` cost is a list append);
  2. one jitted SPMD step (parallel/round_step.py) computes latency,
     counter-based loss, and clamped arrival times shard-locally, routes
     each packet's metadata to its destination shard with `lax.all_to_all`
     over the ICI, and reduces the conservative barrier's global
     min-next-event-time with `lax.pmin`;
  3. the host runtime consumes the exchanged (index, time) pairs to
     enqueue packet events into destination-shard host inboxes; packets
     that exceeded the fixed exchange capacity are delivered host-side
     (a performance fallback, never a correctness one).

Determinism: the loss RNG is threefry keyed by (src_host, packet_seq) —
independent of shard layout and execution order — and events carry
(src_host, seq) tiebreaks, so the packet trace is byte-identical to the
serial scalar scheduler (tests/test_mesh_sim.py, __graft_entry__'s
dryrun_multichip).
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.event import Event, KIND_PACKET
from shadow_tpu.core.rng import STREAM_PACKET_LOSS, mix_key
from shadow_tpu.net import packet as pktmod
from shadow_tpu.ops.propagate import _bucket
from shadow_tpu.parallel.round_step import HOST_AXIS, build_sharded_round_step

_I64_MAX = (1 << 63) - 1


class MeshPropagator:
    """Drop-in for ScalarPropagator/TpuPropagator over a device mesh.

    `finish_round()` returns the *global* next-event time (the `pmin`
    barrier over local host events and in-flight deliveries), so the
    Manager's Python-side min-reduction is bypassed entirely —
    `provides_barrier` tells it so.
    """

    provides_barrier = True

    def __init__(self, hosts, dns, latency_ns, loss_thresholds, seed: int,
                 bootstrap_end_ns: int, n_shards: int,
                 exchange_capacity: int = 1 << 12, runahead=None,
                 devices=None, max_batch: int = 1 << 20):
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        if len(devices) < n_shards:
            raise ValueError(
                f"tpu_shards={n_shards} but only {len(devices)} devices "
                f"visible; lower tpu_shards or add devices")
        self.mesh = Mesh(np.array(devices[:n_shards]), (HOST_AXIS,))
        self.hosts = hosts
        self.dns = dns
        self.n_shards = n_shards
        # Contiguous partition: shard s owns hosts [s*H, (s+1)*H).
        self.hosts_per_shard = -(-len(hosts) // n_shards)
        self.exchange_capacity = exchange_capacity
        k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
        self.step = build_sharded_round_step(
            self.mesh, np.asarray(latency_ns, dtype=np.int64),
            np.asarray(loss_thresholds, dtype=np.int64), k0, k1,
            exchange_capacity)
        self.bootstrap_end = bootstrap_end_ns
        self.runahead = runahead
        # Device-memory bound: per-shard batch width per dispatch, sized
        # so one dispatch never exceeds ~max_batch packets globally.
        self.max_shard_batch = max(1, max_batch // n_shards)
        self.window_end = 0
        self._outboxes: list[list] = [[] for _ in range(n_shards)]
        # Observability (mirrors TpuPropagator's counters).
        self.rounds_dispatched = 0
        self.packets_batched = 0
        self.packets_exchanged = 0
        self.packets_overflowed = 0

    # ------------------------------------------------------------------

    def begin_round(self, window_start: int, window_end: int) -> None:
        self.window_end = window_end

    def send(self, src_host, packet) -> None:
        dst_id = self.dns.host_id_for_ip(packet.dst_ip)
        if dst_id is None:
            src_host.trace_drop(packet, "no-route")
            return
        self._outboxes[src_host.id // self.hosts_per_shard].append(
            (src_host, self.hosts[dst_id], src_host.next_event_seq(),
             packet, src_host.now(), packet.is_empty_control()))

    # ------------------------------------------------------------------

    def set_nt(self, nt: np.ndarray) -> None:
        """Adopt the Manager's shared next-event snapshot (one int64
        slot per host, incrementally maintained by host execute-end
        writes, inbox deliveries, and engine pushes).  Turns the
        per-round barrier input from an O(N) Python host scan into one
        vectorized copy, and lets the Manager's idle-host filter stay
        on in mesh mode."""
        self._nt = nt

    def _host_next_events(self) -> np.ndarray:
        """Per-host local next-event times, padded to [S, H] with +inf.

        Safe to read here: in mesh mode nothing is delivered mid-round
        (send() only buffers), so the snapshot is quiescent between
        `Host.execute` returning and this call."""
        from shadow_tpu.core.simtime import TIME_NEVER
        S, H = self.n_shards, self.hosts_per_shard
        hne = np.full(S * H, _I64_MAX, dtype=np.int64)
        nt = getattr(self, "_nt", None)
        if nt is None:
            # Standalone use (tests build the propagator directly).
            for h in self.hosts:
                t = h.next_event_time()
                if t is not None:
                    hne[h.id] = t
        else:
            n = len(nt)
            hne[:n] = nt
            hne[:n][hne[:n] >= TIME_NEVER] = _I64_MAX
        return hne.reshape(S, H)

    def finish_round(self):
        """Run the SPMD round step and deliver its outputs.

        Returns the global min next-event time (int) or None when no
        events remain anywhere — the round loop's next window start.
        """
        outboxes = self._outboxes
        total = sum(len(ob) for ob in outboxes)
        hne = self._host_next_events()
        if total == 0:
            m = int(hne.min())
            return m if m < _I64_MAX else None

        # Honor the device-memory bound: oversized rounds dispatch as
        # several column chunks of the per-shard outboxes; chunk order
        # preserves per-source emission order, so determinism holds.
        widest = max(len(ob) for ob in outboxes)
        barrier = _I64_MAX
        for lo in range(0, widest, self.max_shard_batch):
            bm = self._dispatch(
                [ob[lo:lo + self.max_shard_batch] for ob in outboxes], hne)
            barrier = min(barrier, bm)
        for ob in outboxes:
            ob.clear()
        self.packets_batched += total
        return barrier if barrier < _I64_MAX else None

    def _dispatch(self, outboxes: list[list], hne: np.ndarray) -> int:
        S = self.n_shards
        B = _bucket(max(len(ob) for ob in outboxes))
        src_node = np.zeros((S, B), dtype=np.int32)
        dst_node = np.zeros((S, B), dtype=np.int32)
        dst_shard = np.zeros((S, B), dtype=np.int32)
        src_host = np.zeros((S, B), dtype=np.int64)
        pkt_seq = np.zeros((S, B), dtype=np.uint32)
        t_send = np.zeros((S, B), dtype=np.int64)
        is_ctl = np.zeros((S, B), dtype=bool)
        valid = np.zeros((S, B), dtype=bool)
        H = self.hosts_per_shard
        for s, ob in enumerate(outboxes):
            n = len(ob)
            if n == 0:
                continue
            src_h, dst_h, _seq, pkts, ts, ctl = zip(*ob)
            src_node[s, :n] = np.fromiter(
                (h.node_index for h in src_h), np.int32, n)
            dst_node[s, :n] = np.fromiter(
                (h.node_index for h in dst_h), np.int32, n)
            dst_shard[s, :n] = np.fromiter(
                (h.id // H for h in dst_h), np.int32, n)
            src_host[s, :n] = np.fromiter((h.id for h in src_h), np.int64, n)
            pkt_seq[s, :n] = np.fromiter(
                (p.seq & 0xFFFFFFFF for p in pkts), np.uint32, n)
            t_send[s, :n] = ts
            is_ctl[s, :n] = ctl
            valid[s, :n] = True

        out = self.step(src_node, dst_node, dst_shard, src_host, pkt_seq,
                        t_send, is_ctl, valid, hne,
                        np.int64(self.window_end),
                        np.int64(self.bootstrap_end))
        (deliver, keep, overflow, reachable, lossy, recv_idx, recv_time,
         barrier_min, min_latency) = (np.asarray(o) for o in out)
        self.rounds_dispatched += 1

        ml = int(min_latency.min())
        if self.runahead is not None and ml < _I64_MAX:
            self.runahead.update_lowest_used_latency(ml)

        # Exchanged deliveries: recv_idx[s, j, c] = index into shard j's
        # outbox of a packet destined for shard s (slot order preserves
        # per-source emission order). argwhere over the sparse sentinel
        # buffer, then plain-int access (numpy scalar indexing in the
        # loop is the slow path — see ops/propagate.py's .tolist() note).
        hits = np.argwhere(recv_idx >= 0)
        if hits.size:
            idx_hit = recv_idx[hits[:, 0], hits[:, 1], hits[:, 2]].tolist()
            time_hit = recv_time[hits[:, 0], hits[:, 1], hits[:, 2]].tolist()
            src_shard_hit = hits[:, 1].tolist()
            for j, i, t in zip(src_shard_hit, idx_hit, time_hit):
                src_h, dst_h, seq, pkt, _ts, _ = outboxes[j][i]
                pkt.arrival_time = t
                dst_h.deliver_packet_event(
                    Event(t, KIND_PACKET, src_h.id, seq, pkt))
            self.packets_exchanged += len(idx_hit)

        # Host-side paths: capacity overflow (delivered anyway — the
        # docstring's promise) and drop tracing.
        for s, ob in enumerate(outboxes):
            if not ob:
                continue
            n = len(ob)
            keep_l = keep[s, :n].tolist()
            over_l = overflow[s, :n].tolist()
            deliver_l = deliver[s, :n].tolist()
            reach_l = reachable[s, :n].tolist()
            lossy_l = lossy[s, :n].tolist()
            for i, (src_h, dst_h, seq, pkt, ts, _) in enumerate(ob):
                if over_l[i]:
                    t = deliver_l[i]
                    pkt.arrival_time = t
                    dst_h.deliver_packet_event(
                        Event(t, KIND_PACKET, src_h.id, seq, pkt))
                    self.packets_overflowed += 1
                elif not keep_l[i]:
                    if not reach_l[i]:
                        src_h.trace_drop(pkt, "unreachable", at_time=ts)
                    elif lossy_l[i]:
                        pkt.record(pktmod.ST_INET_DROPPED)
                        src_h.trace_drop(pkt, "inet-loss", at_time=ts)

        return int(barrier_min.min())
