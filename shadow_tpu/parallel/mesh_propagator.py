"""MeshPropagator: hosts sharded across a device mesh.

The multi-device propagation backend behind `--scheduler=tpu` with
`experimental.tpu_shards > 1`. It is the TPU-native analog of the
reference's scale-out story (worker threads over locked per-host event
queues, src/main/core/worker.rs:597-607 + manager.rs:447-487): hosts are
partitioned into contiguous shards, one device per shard; each round

  1. every host's emitted packets are buffered into its shard's outbox
     (the only `send()` cost is a list append);
  2. one jitted SPMD step (parallel/round_step.py) computes latency,
     counter-based loss, and clamped arrival times shard-locally, routes
     each packet's metadata to its destination shard with `lax.all_to_all`
     over the ICI, and reduces the conservative barrier's global
     min-next-event-time with `lax.pmin`;
  3. the host runtime consumes the exchanged (index, time) pairs to
     enqueue packet events into destination-shard host inboxes; packets
     that exceeded the fixed exchange capacity are delivered host-side
     (a performance fallback, never a correctness one).

Determinism: the loss RNG is threefry keyed by (src_host, packet_seq) —
independent of shard layout and execution order — and events carry
(src_host, seq) tiebreaks, so the packet trace is byte-identical to the
serial scalar scheduler (tests/test_mesh_sim.py, __graft_entry__'s
dryrun_multichip).
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.rng import STREAM_PACKET_LOSS, mix_key
from shadow_tpu.net import packet as pktmod
from shadow_tpu.ops.propagate import (DeviceRouteModel, _bucket,
                                      deliver_engine_exports,
                                      deliver_to_host)
from shadow_tpu.parallel.round_step import HOST_AXIS, build_sharded_round_step

_I64_MAX = (1 << 63) - 1


class MeshPropagator:
    """Drop-in for ScalarPropagator/TpuPropagator over a device mesh.

    `finish_round()` returns the *global* next-event time (the `pmin`
    barrier over local host events and in-flight deliveries), so the
    Manager's Python-side min-reduction is bypassed entirely —
    `provides_barrier` tells it so.
    """

    provides_barrier = True

    def __init__(self, hosts, dns, latency_ns, loss_thresholds, seed: int,
                 bootstrap_end_ns: int, n_shards: int,
                 exchange_capacity: int = 1 << 12, runahead=None,
                 devices=None, max_batch: int = 1 << 20,
                 min_device_batch: int = 2048):
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        if len(devices) < n_shards:
            raise ValueError(
                f"tpu_shards={n_shards} but only {len(devices)} devices "
                f"visible; lower tpu_shards or add devices")
        self.mesh = Mesh(np.array(devices[:n_shards]), (HOST_AXIS,))
        self.hosts = hosts
        self.dns = dns
        self.n_shards = n_shards
        # Contiguous partition: shard s owns hosts [s*H, (s+1)*H).
        self.hosts_per_shard = -(-len(hosts) // n_shards)
        self.exchange_capacity = exchange_capacity
        k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
        self.step = build_sharded_round_step(
            self.mesh, np.asarray(latency_ns, dtype=np.int64),
            np.asarray(loss_thresholds, dtype=np.int64), k0, k1,
            exchange_capacity)
        self.bootstrap_end = bootstrap_end_ns
        self.runahead = runahead
        # Device-memory bound: per-shard batch width per dispatch, sized
        # so one dispatch never exceeds ~max_batch packets globally.
        self.max_shard_batch = max(1, max_batch // n_shards)
        self.window_end = 0
        self._outboxes: list[list] = [[] for _ in range(n_shards)]
        # Native (C++) data-plane engine, set by the Manager when the
        # sharded backend and the engine coexist: engine hosts batch
        # their sends engine-side; _engine_mesh_round consumes the
        # exported columns through the same SPMD step.
        self.engine = None
        # Online cost model for the ENGINE rounds (the object-path
        # outbox always rides the device step — it provides the
        # barrier): the C++ engine's own finish_round is bit-identical
        # to the sharded step, so routing between them is purely a
        # performance choice (ops/propagate.DeviceRouteModel).
        self.route = DeviceRouteModel(min_device_batch,
                                      kind=f"mesh{n_shards}")
        # Chunk bucket sizes the sharded step has already XLA-compiled:
        # the route model's timing must not record a dispatch whose
        # chunk shape compiled inside the timed region (the model keys
        # its own guard on the ROUND bucket, which differs).
        self._step_compiled: set[int] = set()
        # Observability (mirrors TpuPropagator's counters).  `wall` is
        # the flight recorder's wall channel (or None): the SPMD
        # step's dispatch+sync is the conservative barrier, recorded
        # as the "barrier" phase.
        self.wall = None
        self.rounds_dispatched = 0
        self.packets_batched = 0
        self.packets_exchanged = 0
        self.packets_overflowed = 0
        self.packets_engine = 0  # of batched: exported by the C++ engine
        # Auditability (VERDICT r3): accelerator vs host dispatch split.
        self.rounds_device = 0
        self.packets_device = 0
        # Always-on exchange wall (ns): the sharded step's dispatch +
        # barrier sync per round, credited to metrics.wall.dispatch
        # (ISSUE 11 satellite) independent of the flight recorder.
        self.exchange_wall_ns = 0
        # Last engine round size, for the span gate (TpuPropagator
        # twin): a measured-winning device keeps per-round dispatches.
        self._last_engine_n = 0

    @property
    def _outbox(self):
        """Truthy iff any shard outbox holds undelivered packets —
        the manager's span/checkpoint boundary checks read this the
        same way they read TpuPropagator's flat outbox."""
        for ob in self._outboxes:
            if ob:
                return ob
        return None

    def span_gate(self) -> bool:
        """May the manager serve the next rounds with the C++ span
        loop? (TpuPropagator twin.)  False when the route model has
        MEASURED the sharded device step winning at the typical
        engine-round size."""
        return not self.route.device_measured_winning(
            self._last_engine_n)

    # ------------------------------------------------------------------

    def begin_round(self, window_start: int, window_end: int) -> None:
        self.window_end = window_end

    def send(self, src_host, packet) -> None:
        if src_host.link_down:
            # NIC link down (docs/ROBUSTNESS.md): egress drop before
            # the event-seq draw — the same position as the scalar /
            # single-shard / engine twins, so the seq stream (and with
            # it the packet trace) is shard-layout-independent.
            src_host.trace_drop(packet, "link-down")
            return
        dst_id = self.dns.host_id_for_ip(packet.dst_ip)
        if dst_id is None:
            src_host.trace_drop(packet, "no-route")
            return
        self._outboxes[src_host.id // self.hosts_per_shard].append(
            (src_host, self.hosts[dst_id], src_host.next_event_seq(),
             packet, src_host.now(), packet.is_empty_control()))

    # ------------------------------------------------------------------

    def set_nt(self, nt: np.ndarray) -> None:
        """Adopt the Manager's shared next-event snapshot (one int64
        slot per host, incrementally maintained by host execute-end
        writes, inbox deliveries, and engine pushes).  Turns the
        per-round barrier input from an O(N) Python host scan into one
        vectorized copy, and lets the Manager's idle-host filter stay
        on in mesh mode."""
        self._nt = nt

    def _host_next_events(self) -> np.ndarray:
        """Per-host local next-event times, padded to [S, H] with +inf.

        Safe to read here: in mesh mode nothing is delivered mid-round
        (send() only buffers), so the snapshot is quiescent between
        `Host.execute` returning and this call."""
        from shadow_tpu.core.simtime import TIME_NEVER
        S, H = self.n_shards, self.hosts_per_shard
        hne = np.full(S * H, _I64_MAX, dtype=np.int64)
        nt = getattr(self, "_nt", None)
        if nt is None:
            # Standalone use (tests build the propagator directly).
            for h in self.hosts:
                t = h.next_event_time()
                if t is not None:
                    hne[h.id] = t
        else:
            n = len(nt)
            hne[:n] = nt
            hne[:n][hne[:n] >= TIME_NEVER] = _I64_MAX
        return hne.reshape(S, H)

    def finish_round(self):
        """Run the SPMD round step and deliver its outputs.

        Returns the global min next-event time (int) or None when no
        events remain anywhere — the round loop's next window start.
        """
        outboxes = self._outboxes
        total = sum(len(ob) for ob in outboxes)
        eng = self.engine
        n_eng = eng.round_size() if eng is not None else 0
        hne = self._host_next_events()
        if total == 0 and n_eng == 0:
            m = int(hne.min())
            return m if m < _I64_MAX else None

        barrier = _I64_MAX
        if total:
            # Honor the device-memory bound: oversized rounds dispatch
            # as several column chunks of the per-shard outboxes; chunk
            # order preserves per-source emission order, so determinism
            # holds.
            widest = max(len(ob) for ob in outboxes)
            for lo in range(0, widest, self.max_shard_batch):
                bm = self._dispatch(
                    [ob[lo:lo + self.max_shard_batch] for ob in outboxes],
                    hne)
                barrier = min(barrier, bm)
            for ob in outboxes:
                ob.clear()
            self.packets_batched += total
        if n_eng:
            # Engine-batched sends (native-plane hosts): decisions come
            # off the same sharded device step; the engine applies them
            # (deliveries into engine inboxes, drops traced) in one C
            # call.
            bm = self._engine_mesh_round(n_eng, hne)
            barrier = min(barrier, bm)
            self.packets_batched += n_eng
            self.packets_engine += n_eng
        return barrier if barrier < _I64_MAX else None

    def _engine_mesh_round(self, n: int, hne: np.ndarray) -> int:
        """Run the engine's round outbox through the sharded SPMD step.

        The engine exports its round as flat columns (engine emission
        order); rows partition by source shard (src_host //
        hosts_per_shard — the same contiguous partition the Python
        hosts use), each shard's slice rides the device step in order,
        and the flat keep/deliver/drop decisions scatter back through
        `Engine::scatter_round`, which delivers into engine inboxes and
        exports packets whose destination host runs the object path.
        Bit-identical to `Engine::finish_round`'s own math by
        construction (same matrices, same threefry keying) — so the
        cost model may route small rounds entirely into the engine's
        C++ twin when the device dispatch would lose (a virtual CPU
        mesh or a tunnelled chip pays ~ms per dispatch)."""
        import time as _time

        eng = self.engine
        self._last_engine_n = n
        nb = _bucket(n)
        t0 = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
        if not self.route.use_device(n, nb):
            _nf, md, ml, exports = eng.finish_round(self.window_end)
            self.route.record_host(_time.perf_counter_ns() - t0, n)  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
            self.rounds_dispatched += 1
            if self.runahead is not None and ml < _I64_MAX:
                self.runahead.update_lowest_used_latency(ml)
            if exports is not None:
                deliver_engine_exports(self.hosts, exports)
            return min(int(hne.min()), md)

        sn_b, dn_b, dh_b, sh_b, ps_b, ts_b, ctl_b = eng.export_round()
        src_node = np.frombuffer(sn_b, np.int32)
        dst_node = np.frombuffer(dn_b, np.int32)
        dst_host = np.frombuffer(dh_b, np.int32)
        src_host = np.frombuffer(sh_b, np.int64)
        pkt_seq = np.frombuffer(ps_b, np.uint32)
        t_send = np.frombuffer(ts_b, np.int64)
        is_ctl = np.frombuffer(ctl_b, np.uint8).astype(bool)

        S, H = self.n_shards, self.hosts_per_shard
        src_shard = src_host // H
        shard_idx = [np.flatnonzero(src_shard == s) for s in range(S)]
        keep_f = np.zeros(n, dtype=np.uint8)
        deliver_f = np.zeros(n, dtype=np.int64)
        reach_f = np.zeros(n, dtype=np.uint8)
        lossy_f = np.zeros(n, dtype=np.uint8)

        barrier = _I64_MAX
        fresh_compile = False
        widest = max(len(ix) for ix in shard_idx)
        for lo in range(0, widest, self.max_shard_batch):
            chunks = [ix[lo:lo + self.max_shard_batch] for ix in shard_idx]
            B = _bucket(max(len(c) for c in chunks))
            if B not in self._step_compiled:
                self._step_compiled.add(B)
                fresh_compile = True
            sn = np.zeros((S, B), dtype=np.int32)
            dn = np.zeros((S, B), dtype=np.int32)
            ds = np.zeros((S, B), dtype=np.int32)
            sh = np.zeros((S, B), dtype=np.int64)
            ps = np.zeros((S, B), dtype=np.uint32)
            ts = np.zeros((S, B), dtype=np.int64)
            ctl = np.zeros((S, B), dtype=bool)
            valid = np.zeros((S, B), dtype=bool)
            for s, c in enumerate(chunks):
                m = len(c)
                if m == 0:
                    continue
                sn[s, :m] = src_node[c]
                dn[s, :m] = dst_node[c]
                ds[s, :m] = dst_host[c] // H
                sh[s, :m] = src_host[c]
                ps[s, :m] = pkt_seq[c]
                ts[s, :m] = t_send[c]
                ctl[s, :m] = is_ctl[c]
                valid[s, :m] = True

            _w = self.wall
            _tw = _w.now() if _w is not None else 0
            _tx = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] exchange-wall telemetry (metrics.wall.dispatch)
            out = self.step(sn, dn, ds, sh, ps, ts, ctl, valid, hne,
                            np.int64(self.window_end),
                            np.int64(self.bootstrap_end))
            (deliver, keep, overflow, reachable, lossy, _recv_idx,
             _recv_time, barrier_min, min_latency) = \
                (np.asarray(o) for o in out)
            self.exchange_wall_ns += _time.perf_counter_ns() - _tx  # shadow-lint: allow[wall-clock] exchange-wall telemetry (metrics.wall.dispatch)
            if _w is not None:
                # The asarray reads block on the all_to_all exchange:
                # this IS the conservative barrier wait.
                _w.add("barrier", _w.now() - _tw, _tw)
            self.rounds_dispatched += 1
            self.rounds_device += 1
            self.packets_device += sum(len(c) for c in chunks)
            ml = int(min_latency.min())
            if self.runahead is not None and ml < _I64_MAX:
                self.runahead.update_lowest_used_latency(ml)
            barrier = min(barrier, int(barrier_min.min()))
            for s, c in enumerate(chunks):
                m = len(c)
                if m == 0:
                    continue
                keep_f[c] = keep[s, :m]
                deliver_f[c] = deliver[s, :m]
                reach_f[c] = reachable[s, :m]
                lossy_f[c] = lossy[s, :m]
            self.packets_exchanged += int((keep & ~overflow).sum())
            self.packets_overflowed += int(overflow.sum())

        _nf, _md, _ml, exports = eng.scatter_round(
            keep_f, deliver_f, reach_f, lossy_f)
        self.route.record_device(nb, _time.perf_counter_ns() - t0, n,  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
                                 fresh_compile=fresh_compile)
        if exports is not None:
            deliver_engine_exports(self.hosts, exports)
        return barrier

    def _dispatch(self, outboxes: list[list], hne: np.ndarray) -> int:
        S = self.n_shards
        B = _bucket(max(len(ob) for ob in outboxes))
        self._step_compiled.add(B)  # object path warms the same program
        src_node = np.zeros((S, B), dtype=np.int32)
        dst_node = np.zeros((S, B), dtype=np.int32)
        dst_shard = np.zeros((S, B), dtype=np.int32)
        src_host = np.zeros((S, B), dtype=np.int64)
        pkt_seq = np.zeros((S, B), dtype=np.uint32)
        t_send = np.zeros((S, B), dtype=np.int64)
        is_ctl = np.zeros((S, B), dtype=bool)
        valid = np.zeros((S, B), dtype=bool)
        H = self.hosts_per_shard
        for s, ob in enumerate(outboxes):
            n = len(ob)
            if n == 0:
                continue
            src_h, dst_h, _seq, pkts, ts, ctl = zip(*ob)
            src_node[s, :n] = np.fromiter(
                (h.node_index for h in src_h), np.int32, n)
            dst_node[s, :n] = np.fromiter(
                (h.node_index for h in dst_h), np.int32, n)
            dst_shard[s, :n] = np.fromiter(
                (h.id // H for h in dst_h), np.int32, n)
            src_host[s, :n] = np.fromiter((h.id for h in src_h), np.int64, n)
            pkt_seq[s, :n] = np.fromiter(
                (p.seq & 0xFFFFFFFF for p in pkts), np.uint32, n)
            t_send[s, :n] = ts
            is_ctl[s, :n] = ctl
            valid[s, :n] = True

        _w = self.wall
        _t0 = _w.now() if _w is not None else 0
        import time as _time
        _tx = _time.perf_counter_ns()  # shadow-lint: allow[wall-clock] exchange-wall telemetry (metrics.wall.dispatch)
        out = self.step(src_node, dst_node, dst_shard, src_host, pkt_seq,
                        t_send, is_ctl, valid, hne,
                        np.int64(self.window_end),
                        np.int64(self.bootstrap_end))
        (deliver, keep, overflow, reachable, lossy, recv_idx, recv_time,
         barrier_min, min_latency) = (np.asarray(o) for o in out)
        self.exchange_wall_ns += _time.perf_counter_ns() - _tx  # shadow-lint: allow[wall-clock] exchange-wall telemetry (metrics.wall.dispatch)
        if _w is not None:
            # The asarray reads block on the all_to_all exchange: this
            # IS the conservative barrier wait.
            _w.add("barrier", _w.now() - _t0, _t0)
        self.rounds_dispatched += 1
        self.rounds_device += 1
        self.packets_device += sum(len(ob) for ob in outboxes)

        ml = int(min_latency.min())
        if self.runahead is not None and ml < _I64_MAX:
            self.runahead.update_lowest_used_latency(ml)

        # Exchanged deliveries: recv_idx[s, j, c] = index into shard j's
        # outbox of a packet destined for shard s (slot order preserves
        # per-source emission order). argwhere over the sparse sentinel
        # buffer, then plain-int access (numpy scalar indexing in the
        # loop is the slow path — see ops/propagate.py's .tolist() note).
        hits = np.argwhere(recv_idx >= 0)
        if hits.size:
            idx_hit = recv_idx[hits[:, 0], hits[:, 1], hits[:, 2]].tolist()
            time_hit = recv_time[hits[:, 0], hits[:, 1], hits[:, 2]].tolist()
            src_shard_hit = hits[:, 1].tolist()
            for j, i, t in zip(src_shard_hit, idx_hit, time_hit):
                src_h, dst_h, seq, pkt, _ts, _ = outboxes[j][i]
                deliver_to_host(dst_h, t, src_h.id, seq, pkt)
            self.packets_exchanged += len(idx_hit)

        # Host-side paths: capacity overflow (delivered anyway — the
        # docstring's promise) and drop tracing.
        for s, ob in enumerate(outboxes):
            if not ob:
                continue
            n = len(ob)
            keep_l = keep[s, :n].tolist()
            over_l = overflow[s, :n].tolist()
            deliver_l = deliver[s, :n].tolist()
            reach_l = reachable[s, :n].tolist()
            lossy_l = lossy[s, :n].tolist()
            for i, (src_h, dst_h, seq, pkt, ts, _) in enumerate(ob):
                if over_l[i]:
                    deliver_to_host(dst_h, deliver_l[i], src_h.id, seq,
                                    pkt)
                    self.packets_overflowed += 1
                elif not keep_l[i]:
                    if not reach_l[i]:
                        src_h.trace_drop(pkt, "unreachable", at_time=ts)
                    elif lossy_l[i]:
                        pkt.record(pktmod.ST_INET_DROPPED)
                        src_h.trace_drop(pkt, "inet-loss", at_time=ts)

        return int(barrier_min.min())
