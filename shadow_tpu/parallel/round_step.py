"""Multi-device sharded round step.

The reference scales with OS threads over shared memory (scheduler/
worker, SURVEY.md section 2.1); the multi-chip analog shards *hosts*
across devices on a `jax.sharding.Mesh` axis:

- each device owns a contiguous shard of hosts and the packet batch
  those hosts emitted this round;
- propagation math (latency gather, threefry loss, clamp) runs
  shard-locally — identical to the single-chip kernel;
- packets are exchanged to their destination shard with
  `lax.all_to_all` over the ICI (the device-resident replacement for
  the reference's locked per-host event queues, worker.rs:597-607);
- the conservative barrier's global min-next-event-time is a
  `lax.pmin` over the mesh axis (replacing manager.rs:447-487's
  thread-reduction).

The exchange uses fixed per-shard-pair capacity (static shapes: XLA
requirement); overflow falls back to host-side delivery, which only
affects performance, never correctness, because the host runtime
re-checks every delivered packet.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.rng import (STREAM_EXAMPLE_BATCH, mix_key,
                                 threefry2x32_jax, threefry2x32_np)
from shadow_tpu.core.simtime import TIME_NEVER

_I64_MAX = (1 << 63) - 1

HOST_AXIS = "hosts"


def build_sharded_round_step(mesh, latency_ns: np.ndarray,
                             thresholds: np.ndarray, k0: int, k1: int,
                             exchange_capacity: int):
    """Returns a jitted SPMD round step over `mesh` (axis 'hosts').

    Per-shard inputs (leading dim = n_shards when called globally):
      src_node, dst_node : int32[S, B]   packet endpoints (graph nodes)
      dst_shard          : int32[S, B]   destination host's shard index
      src_host, pkt_seq  : int64/uint32[S, B]
      t_send             : int64[S, B]
      is_ctl, valid      : bool[S, B]
      host_next_event    : int64[S, H]   per-host local next-event times
      window_end, bootstrap_end : int64 scalars (replicated)

    Returns:
      deliver  : int64[S, B] arrival times (computed on owner shard)
      keep     : bool[S, B]
      overflow : bool[S, B]  kept but exceeded the exchange capacity
      reachable, lossy : bool[S, B]  drop diagnostics for tracing
      recv_idx, recv_time : exchanged packet index/time per source shard
      barrier_min : int64[S] global min next event (pmin over shards)
      min_latency : int64[S] global min kept latency (dynamic runahead)
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    lat = jnp.asarray(latency_ns, dtype=jnp.int64)
    thr = jnp.asarray(thresholds, dtype=jnp.int64)
    key0 = jnp.uint32(k0)
    key1 = jnp.uint32(k1)
    n_shards = mesh.shape[HOST_AXIS]

    def shard_fn(src_node, dst_node, dst_shard, src_host, pkt_seq, t_send,
                 is_ctl, valid, host_next_event, window_end, bootstrap_end):
        # Leading singleton shard dim inside shard_map; flatten it.
        src_node = src_node[0]
        dst_node = dst_node[0]
        dst_shard = dst_shard[0]
        src_host = src_host[0]
        pkt_seq = pkt_seq[0]
        t_send = t_send[0]
        is_ctl = is_ctl[0]
        valid = valid[0]
        host_next_event = host_next_event[0]

        latency = lat[src_node, dst_node]
        reachable = latency < TIME_NEVER
        bits, _ = threefry2x32_jax(key0, key1, src_host.astype(jnp.uint32),
                                   pkt_seq)
        lossy = (bits.astype(jnp.int64) < thr[src_node, dst_node]) \
            & jnp.logical_not(is_ctl) & (t_send >= bootstrap_end)
        deliver = jnp.maximum(t_send + latency, window_end)
        keep = valid & reachable & jnp.logical_not(lossy)

        # ---- Exchange: route kept packets to their destination shard.
        # Fixed capacity C per destination shard; position within the
        # outgoing block assigned by stable cumulative count so ordering
        # (src_host, seq) is preserved per source shard.
        C = exchange_capacity
        # rank of packet i among kept packets with the same dst_shard
        onehot = (dst_shard[None, :] == jnp.arange(n_shards)[:, None]) & keep
        rank = jnp.cumsum(onehot, axis=1) - 1          # [n_shards, B]
        slot_in_dst = jnp.take_along_axis(
            rank, dst_shard[None, :], axis=0)[0]        # [B]
        fits = keep & (slot_in_dst < C)
        overflow = keep & jnp.logical_not(fits)

        # One-pass scatter, O(B): non-fitting packets write out of bounds
        # and are dropped.
        flat = jnp.where(fits, dst_shard * C + slot_in_dst, n_shards * C)
        pkt_ids = jnp.arange(src_node.shape[0], dtype=jnp.int32)
        send_idx = jnp.full(n_shards * C, -1, dtype=jnp.int32) \
            .at[flat].set(pkt_ids, mode="drop").reshape(n_shards, C)
        send_time = jnp.full(n_shards * C, _I64_MAX, dtype=jnp.int64) \
            .at[flat].set(deliver, mode="drop").reshape(n_shards, C)

        # all_to_all over the mesh axis (tiled: [n_shards, C] stays
        # [n_shards, C], row j of the result = what shard j sent to us).
        recv_idx = lax.all_to_all(send_idx, HOST_AXIS, 0, 0, tiled=True)
        recv_time = lax.all_to_all(send_time, HOST_AXIS, 0, 0, tiled=True)

        # ---- Barrier: global min over local host events, local in-flight
        # deliveries, and everything we received.
        local_min = jnp.minimum(
            jnp.min(host_next_event),
            jnp.min(jnp.where(keep, deliver, _I64_MAX)))
        barrier_min = lax.pmin(local_min, HOST_AXIS)
        # Dynamic-runahead feedback: smallest latency any *delivered*
        # packet used this round, reduced globally (runahead.rs:61).
        min_latency = lax.pmin(
            jnp.min(jnp.where(keep, latency, _I64_MAX)), HOST_AXIS)

        return (deliver[None], keep[None], overflow[None], reachable[None],
                lossy[None], recv_idx[None], recv_time[None],
                barrier_min[None], min_latency[None])

    specs = P(HOST_AXIS)
    in_specs = (specs,) * 9 + (P(), P())
    out_specs = (specs,) * 7 + (P(HOST_AXIS), P(HOST_AXIS))
    fn = shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs)
    return jax.jit(fn)


def _counter_ints(seed: int, field: int, shape, hi: int) -> np.ndarray:
    """Deterministic integers in [0, hi): counter-based threefry keyed
    by (seed, field, flat index) — same shared-RNG family the
    simulation uses, no sequential draw-order dependence."""
    k0, k1 = mix_key(seed, STREAM_EXAMPLE_BATCH)
    n = int(np.prod(shape))
    b0, _ = threefry2x32_np(np.uint32(k0), np.uint32(k1),
                            np.arange(n, dtype=np.uint32),
                            np.uint32(field))
    return (b0.astype(np.uint64) % np.uint64(hi)).reshape(shape)


def make_example_batch(n_shards: int, hosts_per_shard: int,
                       batch_per_shard: int, num_nodes: int, seed: int = 0):
    """Tiny synthetic per-shard packet batches for dry-runs/tests."""
    S, B, H = n_shards, batch_per_shard, hosts_per_shard
    total_hosts = S * H
    src_host = _counter_ints(seed, 0, (S, B), total_hosts).astype(np.int64)
    dst_host = _counter_ints(seed, 1, (S, B), total_hosts).astype(np.int64)
    return {
        "src_node": (src_host % num_nodes).astype(np.int32),
        "dst_node": (dst_host % num_nodes).astype(np.int32),
        "dst_shard": (dst_host // H).astype(np.int32),
        "src_host": src_host,
        "pkt_seq": _counter_ints(seed, 2, (S, B), 1 << 31).astype(np.uint32),
        "t_send": np.full((S, B), 1_000_000_000, dtype=np.int64),
        "is_ctl": np.zeros((S, B), dtype=bool),
        "valid": np.ones((S, B), dtype=bool),
        "host_next_event": np.full((S, H), 2_000_000_000, dtype=np.int64),
    }
