"""Native data-plane loader + per-simulation wrapper.

The engine (`_netplane`, built from native/netplane.cpp) owns every
host's inet data plane; this module builds/loads the extension and wires
the engine's callbacks back into the Python simulation:

 - status changes   -> proxy StatusOwner.adjust_status (listeners fire
                       at exactly the object path's instants);
 - child born/died  -> proxy registry + object-lifecycle accounting;
 - RNG draws        -> the host's one deterministic stream.

One NativePlane per Manager; hosts share the engine (cross-host packet
handles stay valid end to end).
"""

from __future__ import annotations

import os
import subprocess
import sys

from shadow_tpu.native import LIB_DIR, _SRC_DIR, _stale, isa_stale, mark_isa

R_BLOCK = 1000000  # engine "park on a condition" return (netplane.cpp)

_mod = None
_load_error: str | None = None


def load_netplane():
    """Import (building if stale) the _netplane extension; returns the
    module or None (with the failure recorded for error surfaces)."""
    global _mod, _load_error
    if _mod is not None:
        return _mod
    if _load_error is not None:
        return None
    import sysconfig
    ext = sysconfig.get_config_var("EXT_SUFFIX")
    target = os.path.join(LIB_DIR, f"_netplane{ext}")
    sources = [os.path.join(_SRC_DIR, f)
               for f in ("netplane.cpp", "Makefile")]
    rebuilt = False
    if _stale(target, sources) or isa_stale(target):
        try:
            pre_mtime = os.path.getmtime(target)
        except OSError:
            pre_mtime = None
        # isa_stale: the engine builds with -march=native; an artifact
        # from a different CPU must rebuild, not SIGILL.  Remove the
        # stale artifact (and its ISA sidecar) rather than touching the
        # source: mutating source mtimes races with concurrent builders
        # and perturbs staleness decisions for every other consumer.
        try:
            if os.path.exists(target):
                os.unlink(target)
            if os.path.exists(target + ".cpu"):
                os.unlink(target + ".cpu")
        except OSError:
            pass  # read-only lib dir: let make decide
        proc = subprocess.run(["make", "-C", _SRC_DIR, "netplane"],
                              capture_output=True, text=True)
        if proc.returncode != 0 or not os.path.exists(target):
            if os.path.exists(target) and not _stale(target, sources) \
                    and not isa_stale(target):
                # Unbuildable environment but a source-fresh artifact
                # whose ISA sidecar matches this CPU: trust it.  An
                # artifact of UNVERIFIABLE ISA is never imported — a
                # -march=native mismatch dies by SIGILL, not a clean
                # exception, so the safe degrade is the object path.
                pass
            else:
                _load_error = (f"netplane build failed (exit "
                               f"{proc.returncode}): "
                               f"{proc.stderr[-2000:]}")
                return None
        else:
            # "Rebuilt" must mean make actually relinked: on a
            # read-only lib dir the unlink above fails silently, make
            # sees a fresh target and no-ops with exit 0 — trusting
            # that would import a wrong-ISA artifact.  A real rebuild
            # changes the target's mtime (or creates it).
            try:
                rebuilt = os.path.getmtime(target) != pre_mtime
            except OSError:
                rebuilt = False
            if rebuilt:
                try:
                    mark_isa(target)
                except OSError:
                    pass  # read-only lib dir: rebuilt next process, fine
    if not rebuilt and os.path.exists(target) and isa_stale(target):
        # Read-only lib dir can leave the wrong-ISA artifact in place
        # (unlink failed, make saw it fresh and no-opped).  A
        # -march=native mismatch dies by SIGILL, not a clean exception,
        # so never import it — degrade to the object path instead.
        # (`rebuilt` exempts a build we just made here: it is native to
        # this CPU even when the sidecar could not be written.)
        _load_error = "netplane artifact ISA-stale and not rebuildable"
        return None
    if LIB_DIR not in sys.path:
        sys.path.insert(0, LIB_DIR)
    try:
        import _netplane
    except ImportError as e:  # pragma: no cover
        _load_error = f"netplane import failed: {e}"
        return None
    _mod = _netplane
    return _mod


def native_available() -> bool:
    return load_netplane() is not None


def load_error() -> str | None:
    return _load_error


class NativePlane:
    """Engine + callback bridge for one simulation."""

    def __init__(self, hosts):
        import weakref
        mod = load_netplane()
        if mod is None:
            raise RuntimeError(_load_error or "netplane unavailable")
        self.mod = mod
        self.engine = mod.Engine()
        self._hosts = hosts  # host_id -> Host (list)
        # The engine strong-refs its callbacks; closing the loop with
        # bound methods would make an uncollectable C-held cycle
        # (engine -> method -> plane -> engine).  Weakref trampolines
        # keep the engine's refs pointing away from the plane.
        wself = weakref.ref(self)

        def on_event(kind, hid, tok, a, b, t):
            p = wself()
            if p is not None:
                p._on_event(kind, hid, tok, a, b, t)

        def rng_u64(hid):
            p = wself()
            return p._rng_u64(hid) if p is not None else 0

        self.engine.set_callbacks(on_event, rng_u64)

    def add_host(self, host, qdisc_rr: bool, mtu: int = 1500) -> None:
        self.engine.add_host(host.id, host.ip, host.bw_up_bits,
                             host.bw_down_bits, qdisc_rr, mtu)
        # Per-host TCP stack options (`tcp:` config block): every
        # engine-side connection on this host — app-owned or proxied —
        # inherits them at TcpConn birth.
        self.engine.set_host_tcp(
            host.id, 1 if host.tcp_cc == "dctcp" else 0,
            1 if host.tcp_ecn else 0)
        host.plane = self
        # Move the host RNG stream engine-side (native threefry): the
        # engine draws locally instead of calling back into Python per
        # u64, and Python-side draws delegate through rng_next so the
        # ONE counter keeps the stream identical to the object path.
        rng = host.rng
        self.engine.set_host_rng(host.id, rng._k0, rng._k1, rng._counter)
        rng.attach_engine(self.engine, host.id)

    # -- callbacks (invoked synchronously from inside engine calls) ----

    def _on_event(self, kind: int, hid: int, tok: int, a: int,
                  b: int, t: int) -> None:
        host = self._hosts[hid]
        # During a batched engine run the Python-side clock lags; the
        # callback carries the engine's current instant so listeners
        # (conditions scheduling wakeups at now()) see the right time.
        # max(): in syscall context the engine's clock may be stale
        # instead, and per-host sim time is monotonic.
        if t > host._now:
            host._now = t
        if kind == self.mod.CB_STATUS:
            sock = host._nsocks.get(tok)
            if sock is not None:
                sock.apply_status(host, a, b)
        elif kind == self.mod.CB_CHILD_BORN:
            # tok = listener, a = child: create the proxy at birth so
            # lifecycle accounting and status mirroring start here.
            from shadow_tpu.host.socket_native import TcpSocket
            TcpSocket(host, 0, 0, _tok=a)  # registers itself
        else:  # CB_CHILD_DEAD: pre-accept teardown = its deallocation
            sock = host._nsocks.pop(tok, None)
            if sock is not None:
                from shadow_tpu.utils.object_counter import mark_dealloc
                mark_dealloc(sock)

    def _rng_u64(self, hid: int) -> int:
        return self._hosts[hid].rng.next_u64()
