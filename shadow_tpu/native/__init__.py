"""Native components: build-on-demand + path discovery.

The shim (`libshadowtpu_shim.so`) is C compiled from `native/` at the
repo root; it is LD_PRELOADed into managed processes and must NEVER be
loaded into the simulator process (its constructor installs a seccomp
filter).  The manager talks to it purely through the mmap'd IPC block
(shadow_tpu/host/shim_abi.py), so no host-side native library is
required.
"""

from __future__ import annotations

import os
import subprocess

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_PKG_DIR))
_SRC_DIR = os.path.join(_REPO_ROOT, "native")
LIB_DIR = os.path.join(_PKG_DIR, "lib")

SHIM_SO = os.path.join(LIB_DIR, "libshadowtpu_shim.so")


def _stale(target: str, sources: list[str]) -> bool:
    if not os.path.exists(target):
        return True
    t = os.path.getmtime(target)
    return any(os.path.getmtime(s) > t for s in sources
               if os.path.exists(s))


def _cpu_fingerprint() -> str:
    """ISA identity for -march=native artifacts: a prebuilt engine
    carried to a different CPU (docker cache, copied checkout) must
    rebuild, not SIGILL at the first call."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    import hashlib
                    return hashlib.sha1(line.encode()).hexdigest()[:16]
    except OSError:
        pass
    import platform
    return platform.machine()


def isa_stale(target: str) -> bool:
    """True when `target` was built on a CPU with different ISA flags
    (sidecar written by mark_isa)."""
    try:
        with open(target + ".cpu") as f:
            return f.read().strip() != _cpu_fingerprint()
    except OSError:
        return os.path.exists(target)  # artifact without provenance


def mark_isa(target: str) -> None:
    with open(target + ".cpu", "w") as f:
        f.write(_cpu_fingerprint())


def _ensure_built(so_path: str, target: str, source_names: list[str]) -> str:
    """Build a native component if missing or out of date; return its
    path.  Raises RuntimeError (with the compiler output) when the
    toolchain is unavailable or the build fails, so callers can surface
    a clear error instead of a confusing spawn failure."""
    sources = [os.path.join(_SRC_DIR, f) for f in source_names]
    if not _stale(so_path, sources):
        return so_path
    if not os.path.isdir(_SRC_DIR):
        raise RuntimeError(f"native sources not found at {_SRC_DIR}")
    proc = subprocess.run(["make", "-C", _SRC_DIR, target],
                          capture_output=True, text=True)
    if proc.returncode != 0 or not os.path.exists(so_path):
        raise RuntimeError(
            f"{target} build failed (exit {proc.returncode}):\n"
            f"{proc.stdout}\n{proc.stderr}")
    return so_path


def ensure_shim_built() -> str:
    return _ensure_built(SHIM_SO, "shim",
                         ["shim.c", "shim_trampoline.S", "shim_ipc.h",
                          "Makefile"])


CRYPTO_NOOP_SO = os.path.join(LIB_DIR, "libshadowtpu_crypto_noop.so")


def ensure_crypto_noop_built() -> str:
    """Opt-in crypto no-op preload (ref preload-openssl/crypto.c)."""
    return _ensure_built(CRYPTO_NOOP_SO, "crypto_noop",
                         ["crypto_noop.c", "Makefile"])
