"""Native components: build-on-demand + path discovery.

The shim (`libshadowtpu_shim.so`) is C compiled from `native/` at the
repo root; it is LD_PRELOADed into managed processes and must NEVER be
loaded into the simulator process (its constructor installs a seccomp
filter).  The manager talks to it purely through the mmap'd IPC block
(shadow_tpu/host/shim_abi.py), so no host-side native library is
required.
"""

from __future__ import annotations

import os
import subprocess

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_PKG_DIR))
_SRC_DIR = os.path.join(_REPO_ROOT, "native")
LIB_DIR = os.path.join(_PKG_DIR, "lib")

SHIM_SO = os.path.join(LIB_DIR, "libshadowtpu_shim.so")


def _stale(target: str, sources: list[str]) -> bool:
    if not os.path.exists(target):
        return True
    t = os.path.getmtime(target)
    return any(os.path.getmtime(s) > t for s in sources
               if os.path.exists(s))


def ensure_shim_built() -> str:
    """Build the shim if missing or out of date; return its path.

    Raises RuntimeError (with the compiler output) when the toolchain is
    unavailable or the build fails, so callers can surface a clear error
    instead of a confusing spawn failure.
    """
    sources = [os.path.join(_SRC_DIR, f)
               for f in ("shim.c", "shim_trampoline.S", "shim_ipc.h",
                         "Makefile")]
    if not _stale(SHIM_SO, sources):
        return SHIM_SO
    if not os.path.isdir(_SRC_DIR):
        raise RuntimeError(f"native sources not found at {_SRC_DIR}")
    proc = subprocess.run(["make", "-C", _SRC_DIR, "shim"],
                          capture_output=True, text=True)
    if proc.returncode != 0 or not os.path.exists(SHIM_SO):
        raise RuntimeError(
            f"shim build failed (exit {proc.returncode}):\n"
            f"{proc.stdout}\n{proc.stderr}")
    return SHIM_SO


CRYPTO_NOOP_SO = os.path.join(LIB_DIR, "libshadowtpu_crypto_noop.so")


def ensure_crypto_noop_built() -> str:
    """Build the opt-in crypto no-op preload (ref
    preload-openssl/crypto.c) if missing/stale; return its path."""
    sources = [os.path.join(_SRC_DIR, f)
               for f in ("crypto_noop.c", "Makefile")]
    if not _stale(CRYPTO_NOOP_SO, sources):
        return CRYPTO_NOOP_SO
    if not os.path.isdir(_SRC_DIR):
        raise RuntimeError(f"native sources not found at {_SRC_DIR}")
    proc = subprocess.run(["make", "-C", _SRC_DIR, "crypto_noop"],
                          capture_output=True, text=True)
    if proc.returncode != 0 or not os.path.exists(CRYPTO_NOOP_SO):
        raise RuntimeError(
            f"crypto_noop build failed (exit {proc.returncode}):\n"
            f"{proc.stdout}\n{proc.stderr}")
    return CRYPTO_NOOP_SO
