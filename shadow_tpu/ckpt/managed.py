"""Managed-process checkpointing: restart records + tombstone pickling.

A managed (real-binary) process cannot be snapshotted mid-flight — its
native memory, seccomp state and IPC block live in the OS, not the
simulation.  What CAN be captured, and what a sim farm actually needs
for long-running managed fleets (ROADMAP item 2), is **final-state-
checked restart semantics**: the archive records each managed
process's argv/env/expected_final_state (plus its host's syscall-
channel position for `ckpt info`); resume restarts the binary FRESH at
the snapshot boundary and the run is gated on the recorded expected
final state.  Resumed managed runs therefore carry **no byte-
continuation contract** — the restarted binary re-runs its life — but
two resumes of the same archive are byte-identical to each other
(gated in tests/test_svc.py), and everything non-managed in the sim
still resumes exactly as before.

Mechanics: `write_snapshot` pickles the host graph through
`SnapshotPickler`, whose reducer_override replaces every managed-
owned object — the ManagedProcess/ManagedThread pair, the IPC block
and memory manager, the process's fd-table files (TCP backlog
children included) and any condition whose wakeup or disarm hook
belongs to managed machinery — with a `ManagedTombstone`.  Tombstones
absorb attribute lookups (a pickled bound method of a managed thread
loads as a no-op callable) so unpickling never trips; `purge
_tombstones` then sweeps them out of the restored host (processes,
event heap, interface associations, send queues) before anything runs.
The straight run is never mutated — snapshotting stays a read-only
walk.

Refusals (clear, at snapshot time): a LIVE managed process created by
fork (no spawn_tag) cannot be restart-checked — its lifecycle belongs
to the parent's rerun, which would duplicate it; snapshot before the
fork or after the child exits.
"""

from __future__ import annotations

import io
import pickle
import types

from shadow_tpu.ckpt.format import CkptError


def _tombstone_noop(*_args, **_kwargs):
    return None


class ManagedTombstone:
    """Placeholder a managed-owned object pickles into.  Attribute
    lookups return a no-op callable so bound-method pickles (getattr
    at load time) and defensive getattr probes never raise; calling
    the tombstone itself is also a no-op."""

    __slots__ = ()

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        return _tombstone_noop

    def __call__(self, *args, **kwargs):
        return None

    def __reduce__(self):
        return (ManagedTombstone, ())


def _is_tomb(obj) -> bool:
    return isinstance(obj, ManagedTombstone) or obj is _tombstone_noop


def _managed_types():
    from shadow_tpu.host.futex import FutexTable
    from shadow_tpu.host.managed import (ManagedProcess, ManagedThread,
                                         MemoryManager)
    from shadow_tpu.host.shim_abi import Channel, IpcBlock
    return (ManagedProcess, ManagedThread, MemoryManager, IpcBlock,
            Channel, FutexTable)


def _condition_types():
    from shadow_tpu.host.condition import (ManualCondition,
                                           MultiSyscallCondition,
                                           SyscallCondition)
    return (SyscallCondition, ManualCondition, MultiSyscallCondition)


class SnapshotPickler(pickle.Pickler):
    """Pickler that strips managed-owned objects to tombstones.

    `owned_ids` is the id() set of the managed processes' fd-table
    objects (collect_managed builds it); type-based rules catch the
    managed machinery itself and any condition wired to it."""

    def __init__(self, file, owned_ids: set, protocol: int = 4):
        super().__init__(file, protocol)
        self._owned = owned_ids
        self._mtypes = _managed_types()
        self._ctypes = _condition_types()

    def reducer_override(self, obj):
        if isinstance(obj, self._mtypes) or id(obj) in self._owned:
            return (ManagedTombstone, ())
        if isinstance(obj, self._ctypes):
            # A condition is managed-owned when its wakeup resolves to
            # managed machinery, or when it carries an on_disarm hook
            # (a closure — only the managed futex/fd paths set one).
            wf = getattr(obj, "_wakeup_fn", None)
            owner = getattr(wf, "__self__", None)
            if isinstance(owner, self._mtypes) or id(owner) in self._owned:
                return (ManagedTombstone, ())
            if getattr(obj, "on_disarm", None) is not None:
                return (ManagedTombstone, ())
        if isinstance(obj, types.MethodType):
            owner = obj.__self__
            if isinstance(owner, self._mtypes) or id(owner) in self._owned:
                return (ManagedTombstone, ())
        return NotImplemented


def dumps_hosts(hosts, owned_ids: set) -> bytes:
    buf = io.BytesIO()
    SnapshotPickler(buf, owned_ids).dump(hosts)
    return buf.getvalue()


def managed_domain_error(manager) -> str | None:
    """Why this sim's managed processes cannot be restart-checked
    (None = they can).  Only LIVE fork children refuse: a restarted
    parent re-runs its whole lifecycle, forks included, so a live
    child snapshotted alongside would be duplicated and its final
    state unattributable."""
    from shadow_tpu.host.managed import ManagedProcess
    for host in manager.hosts:
        for proc in host.processes.values():
            if not isinstance(proc, ManagedProcess) or proc.exited:
                continue
            if getattr(proc, "spawn_tag", None) is None:
                return (f"{host.name}/{proc.name} is a live managed "
                        f"process created by fork: restart semantics "
                        f"re-run the parent (which re-forks), so a "
                        f"forked child cannot be restart-checked — "
                        f"snapshot before the fork or after the child "
                        f"exits (docs/CHECKPOINT.md)")
    return None


def collect_managed(manager) -> tuple[list, set]:
    """(restart records, managed-owned object id set).  Records are
    built in (host id, pid) order so byte-identical sims write
    byte-identical archives; the id set feeds SnapshotPickler.
    Read-only — the live run continues untouched except for
    collect_output's incremental fold (idempotent, offsets only)."""
    from shadow_tpu.host.managed import ManagedProcess
    records: list = []
    owned: set = set()
    for host in manager.hosts:
        for pid in sorted(host.processes):
            proc = host.processes[pid]
            if not isinstance(proc, ManagedProcess):
                continue
            for table in (proc.fds, getattr(proc, "fds_low", None)):
                if table is None:
                    continue
                for _fd, f in table.items():
                    owned.add(id(f))
                    # TCP listeners hold not-yet-accepted children the
                    # interface may also reference by 4-tuple.
                    for child in getattr(f, "_accept_q", ()):
                        owned.add(id(child))
            if proc.exited:
                proc.collect_output()
            sc_log = getattr(host, "sc_log", None)
            records.append({
                "host_id": host.id,
                "pid": pid,
                "name": proc.name,
                "spawn_tag": getattr(proc, "spawn_tag", None),
                "argv": list(proc.argv),
                "env": dict(proc.env),
                "expected_final_state": proc.expected_final_state,
                "work_dir": proc.work_dir,
                "exited": bool(proc.exited),
                "exit_code": proc.exit_code,
                "term_signal": proc.term_signal,
                "stdout": bytes(proc.stdout) if proc.exited else b"",
                "stderr": bytes(proc.stderr) if proc.exited else b"",
                # Syscall-channel position at the boundary (`ckpt
                # info`): records this host had emitted so far.
                "sc_records": sc_log.records if sc_log is not None
                              else 0,
            })
    return records, owned


def _orphan_packet(host, p) -> bool:
    """True when `p` (an inbound packet at this host) resolves to no
    association on either interface — after the tombstone sweep that
    means it is stale traffic of the previous managed life.  The
    restart happens AFTER the purge, so a restarted binary re-binding
    the same well-known port can never be matched here."""
    for iface in (host.lo, host.eth0):
        if iface.lookup(p.protocol, p.dst_port, p.src_ip,
                        p.src_port) is not None:
            return False
    return True


def purge_tombstones(host) -> None:
    """Sweep tombstones out of one restored host: dead processes,
    event-heap tasks whose callable collapsed to a no-op, interface
    associations and send queues of stripped sockets — and then the
    previous life's TRAFFIC.  Stale packets must not reach a
    restarted binary that re-binds the same port (a pre-snapshot ping
    delivered to the fresh server would eat its budget), so after the
    association sweep every packet that no longer resolves to a
    receiver is purged: in-flight heap/inbox deliveries silently
    (they sit in no ledger yet), router-queued and relay-parked ones
    as attributed CoDel drops so the fabric conservation invariant
    (enqueued == forwarded + dropped + queued + parked) stays exact."""
    import heapq

    from shadow_tpu.core.event import KIND_PACKET
    for pid in [p for p, proc in host.processes.items()
                if _is_tomb(proc)]:
        del host.processes[pid]
    heap = host.queue._heap
    kept = [row for row in heap
            if not (hasattr(row[4].data, "fn")
                    and _is_tomb(row[4].data.fn))]
    if len(kept) != len(heap):
        heapq.heapify(kept)
        host.queue._heap = kept
    if not host.net_built():
        return
    for iface in (host.lo, host.eth0):
        for key in [k for k, s in iface._assoc.items() if _is_tomb(s)]:
            iface.disassociate(key[0], key[2], key[3], key[4])
        iface._queued = {s for s in iface._queued if not _is_tomb(s)}
        iface._send_heap = [row for row in iface._send_heap
                            if not _is_tomb(row[2])]
        heapq.heapify(iface._send_heap)
        iface._send_ready = type(iface._send_ready)(
            s for s in iface._send_ready if not _is_tomb(s))
    # Stale in-flight deliveries (cross-host packets not yet executed):
    # not in any queue ledger — delete silently.
    heap = host.queue._heap
    kept = [row for row in heap
            if not (row[4].kind == KIND_PACKET
                    and type(row[4].data) is not int
                    and _orphan_packet(host, row[4].data))]
    if len(kept) != len(heap):
        heapq.heapify(kept)
        host.queue._heap = kept
    host._inbox = type(host._inbox)(
        ev for ev in host._inbox
        if not (ev.kind == KIND_PACKET and type(ev.data) is not int
                and _orphan_packet(host, ev.data)))
    # Router-queued stale packets: drop through the CoDel counters +
    # the codel TEL cause so drop attribution and the per-interface
    # byte ledger reconcile exactly.
    codel = host.router._inbound
    kept_q, stale = [], []
    for entry in codel._q:
        (stale if _orphan_packet(host, entry[0])
         else kept_q).append(entry)
    if stale:
        codel._q = type(codel._q)(kept_q)
        for p, _t in stale:
            codel._bytes -= p.total_size()
            codel._drop(p, lambda pk: host.trace_drop(pk, "codel"))
    # Relay-parked packet (popped from the queue, waiting on a bucket
    # refill): per the ledger it is still "inside" — parked-1,
    # dropped+1 balances.
    relay = host.relay_inet_in
    parked = relay._pending_packet
    if parked is not None and _orphan_packet(host, parked):
        relay._pending_packet = None
        codel._drop(parked, lambda pk: host.trace_drop(pk, "codel"))


class _RestartTask:
    """Scheduled at the resume boundary: build a fresh ManagedProcess
    from the restart record and spawn the binary."""

    __slots__ = ("rec",)

    def __init__(self, rec: dict):
        self.rec = rec

    def __call__(self, host) -> None:
        from shadow_tpu.host.managed import ManagedProcess
        rec = self.rec
        # Output goes under the RESUMED run's data directory (_rewire
        # re-points host.data_path exactly like every other artifact);
        # the recorded work_dir is only the fallback for hosts with no
        # data dir — writing into the snapshot-time path would clobber
        # the straight run's tree, or crash where it is unwritable.
        proc = ManagedProcess(
            host, rec["name"], list(rec["argv"]), dict(rec["env"]),
            expected_final_state=rec["expected_final_state"],
            work_dir=getattr(host, "data_path", None)
            or rec["work_dir"])
        proc.strace_mode = host.strace_mode
        if rec["spawn_tag"] is not None:
            proc.spawn_tag = rec["spawn_tag"]
        proc.start_native(host, rec["argv"][0] if rec["argv"] else None)


def restore_managed(manager, records: list, at: int) -> None:
    """Re-create the managed fleet on a resumed manager: exited
    processes come back as final-state husks (their recorded output
    and exit code, judged by the normal expected-final-state sweep);
    live ones restart fresh at the boundary `at`, gated on the
    recorded expected final state."""
    from shadow_tpu.core.event import TaskRef
    from shadow_tpu.host.process import Process
    for rec in records:
        host = manager.hosts[rec["host_id"]]
        if rec["exited"]:
            husk = Process(host, rec["name"], list(rec["argv"]),
                           dict(rec["env"]),
                           expected_final_state=rec
                           ["expected_final_state"])
            # Re-key under the recorded pid: register_process handed
            # out a fresh one, but the husk IS the old process.
            del host.processes[husk.pid]
            host._next_pid -= 1
            husk.pid = husk.pgid = husk.sid = rec["pid"]
            host.processes[rec["pid"]] = husk
            husk.exited = True
            husk.exit_code = rec["exit_code"]
            husk.term_signal = rec["term_signal"]
            husk.stdout = bytearray(rec["stdout"])
            husk.stderr = bytearray(rec["stderr"])
            if rec["spawn_tag"] is not None:
                husk.spawn_tag = rec["spawn_tag"]
            continue
        host.schedule_task_at(max(at, host._now),
                              TaskRef("managed-restart",
                                      _RestartTask(rec)))
