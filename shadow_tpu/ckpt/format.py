"""Checkpoint archive container + the engine plane-blob framing twins.

One snapshot is ONE file::

    CK_HDR    magic, layout version, section count, flags
    n x CK_SEC_HDR   section id, crc32(payload), payload length
    payloads concatenated in table order

Sections are fixed-purpose (CK_SEC_*); `ckpt diff` compares two
archives section by section and names the first differing one, `ckpt
verify` re-checksums every payload and gates on the layout version.
Snapshots of byte-identical simulations are byte-identical files: every
producer serializes maps in sorted order and nothing wall-clock-derived
enters the archive.

The CK_PLANE_* constants at the bottom are TWINS of the same
definitions in native/netplane.cpp (the engine's plane_export blob
framing); analysis pass 1 registers the whole CK_ prefix fail-closed,
so a drifted header constant fails `scripts/lint` instead of silently
misparsing every snapshot.
"""

from __future__ import annotations

import struct
import zlib

CK_MAGIC = 0x5354434B  # "STCK"
CK_VERSION = 1

CK_HDR = struct.Struct("<IIII")  # magic, version, n_sections, flags
CK_HDR_BYTES = 16
assert CK_HDR.size == CK_HDR_BYTES

CK_SEC_HDR = struct.Struct("<IIQ")  # section id, crc32, byte length
CK_SEC_HDR_BYTES = 16
assert CK_SEC_HDR.size == CK_SEC_HDR_BYTES

# Section ids (one purpose each; unknown ids are rejected on read so a
# future layout change must bump CK_VERSION).
CK_SEC_META = 1    # json: round/time/summary scalars + config digest
CK_SEC_HOSTS = 2   # pickle: the complete Python-side host object state
CK_SEC_PLANE = 3   # engine plane blob (netplane.cpp plane_export)
CK_SEC_TRACE = 4   # pickle: sim-time channel continuations + audit
CK_SEC_RNG = 5     # packed (host id u32, rng counter u64) rows
CK_SEC_FAULTS = 6  # json: per-host fault flags + schedule cursor
CK_SEC_MANAGED = 7  # pickle: managed-process restart records
#                     (ckpt/managed.py — final-state-checked restart
#                     semantics; hosts section carries tombstones)

CK_SEC_NAMES = {
    CK_SEC_META: "meta",
    CK_SEC_HOSTS: "hosts",
    CK_SEC_PLANE: "plane",
    CK_SEC_TRACE: "trace",
    CK_SEC_RNG: "rng",
    CK_SEC_FAULTS: "faults",
    CK_SEC_MANAGED: "managed",
}

CK_RNG_ROW = struct.Struct("<IQ")

# ---------------------------------------------------------------------
# Engine plane-blob framing (C++ twins: the CK_* constexprs in
# native/netplane.cpp; registered fail-closed in analysis pass 1).
# plane_export writes [magic, version, n_frames, pad, state_epoch],
# then per-frame [id u32][length u64] — id CK_GLOBAL_FRAME for the one
# engine-global frame, else the host id.
CK_PLANE_MAGIC = 0x53544350  # "STCP"
CK_PLANE_VERSION = 3
CK_PLANE_HDR_BYTES = 24
CK_FRAME_HDR_BYTES = 12
CK_GLOBAL_FRAME = 0xFFFFFFFF

CK_PLANE_HDR = struct.Struct("<IIIIQ")
assert CK_PLANE_HDR.size == CK_PLANE_HDR_BYTES
CK_FRAME_HDR = struct.Struct("<IQ")
assert CK_FRAME_HDR.size == CK_FRAME_HDR_BYTES


class CkptError(RuntimeError):
    """Any checkpoint/resume failure with a user-actionable message."""


def write_archive(path: str, sections: dict[int, bytes]) -> None:
    """Write one snapshot archive; sections keyed by CK_SEC_* id,
    emitted in ascending id order (deterministic bytes)."""
    ids = sorted(sections)
    blob = bytearray()
    blob += CK_HDR.pack(CK_MAGIC, CK_VERSION, len(ids), 0)
    for sid in ids:
        payload = sections[sid]
        blob += CK_SEC_HDR.pack(sid, zlib.crc32(payload) & 0xFFFFFFFF,
                                len(payload))
    for sid in ids:
        blob += sections[sid]
    with open(path, "wb") as f:
        f.write(bytes(blob))


def section_table(path: str) -> list[tuple[int, int, int]]:
    """[(section id, crc32, length)] in file order; validates the
    header (magic + layout version) but reads no payloads."""
    with open(path, "rb") as f:
        hdr = f.read(CK_HDR_BYTES)
        if len(hdr) < CK_HDR_BYTES:
            raise CkptError(f"{path}: shorter than a snapshot header")
        magic, version, n, _flags = CK_HDR.unpack(hdr)
        if magic != CK_MAGIC:
            raise CkptError(f"{path}: not a shadow-tpu snapshot "
                            f"(magic {magic:#x})")
        if version != CK_VERSION:
            raise CkptError(
                f"{path}: snapshot layout version {version} != "
                f"supported {CK_VERSION} (written by a different "
                f"build; re-snapshot or use that build to resume)")
        out = []
        for _ in range(n):
            sh = f.read(CK_SEC_HDR_BYTES)
            if len(sh) < CK_SEC_HDR_BYTES:
                raise CkptError(f"{path}: truncated section table")
            out.append(CK_SEC_HDR.unpack(sh))
    return out


def read_archive(path: str, verify: bool = True) -> dict[int, bytes]:
    """Section id -> payload bytes; checksums verified unless told
    otherwise (ckpt `verify` reports per-section instead of raising)."""
    table = section_table(path)
    out: dict[int, bytes] = {}
    off = CK_HDR_BYTES + CK_SEC_HDR_BYTES * len(table)
    with open(path, "rb") as f:
        f.seek(off)
        for sid, crc, length in table:
            if sid in out:
                raise CkptError(f"{path}: duplicate section {sid}")
            if sid not in CK_SEC_NAMES:
                raise CkptError(f"{path}: unknown section id {sid} "
                                f"(newer layout?)")
            payload = f.read(length)
            if len(payload) != length:
                raise CkptError(f"{path}: truncated section "
                                f"{CK_SEC_NAMES[sid]}")
            if verify and (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise CkptError(f"{path}: checksum mismatch in section "
                                f"{CK_SEC_NAMES[sid]} (corrupt file)")
            out[sid] = payload
        if f.read(1):
            raise CkptError(f"{path}: trailing bytes after the last "
                            f"section")
    return out


def read_meta(path: str) -> dict:
    """Just the meta section (ckpt `info` fast path)."""
    import json
    return json.loads(read_archive(path)[CK_SEC_META].decode())


def parse_plane_frames(blob: bytes) -> tuple[int, dict[int, bytes]]:
    """Engine plane blob -> (state_epoch, {host id -> frame bytes});
    the global frame lands under CK_GLOBAL_FRAME."""
    if len(blob) < CK_PLANE_HDR_BYTES:
        raise CkptError("plane section shorter than its header")
    magic, version, n_frames, _pad, epoch = CK_PLANE_HDR.unpack_from(
        blob, 0)
    if magic != CK_PLANE_MAGIC:
        raise CkptError(f"plane section magic {magic:#x} != expected")
    if version != CK_PLANE_VERSION:
        raise CkptError(f"plane layout version {version} != "
                        f"{CK_PLANE_VERSION}")
    frames: dict[int, bytes] = {}
    off = CK_PLANE_HDR_BYTES
    for _ in range(n_frames):
        if len(blob) - off < CK_FRAME_HDR_BYTES:
            raise CkptError("truncated plane frame table")
        fid, length = CK_FRAME_HDR.unpack_from(blob, off)
        off += CK_FRAME_HDR_BYTES
        if len(blob) - off < length:
            raise CkptError("truncated plane frame")
        frames[fid] = blob[off:off + length]
        off += length
    if off != len(blob):
        raise CkptError("trailing bytes after the last plane frame")
    return epoch, frames


def pack_rng_rows(rows: list[tuple[int, int]]) -> bytes:
    return b"".join(CK_RNG_ROW.pack(hid, ctr) for hid, ctr in rows)


def iter_rng_rows(buf: bytes):
    for off in range(0, len(buf) - len(buf) % CK_RNG_ROW.size,
                     CK_RNG_ROW.size):
        yield CK_RNG_ROW.unpack_from(buf, off)
