"""Deterministic checkpoint/resume + fault injection (docs/CHECKPOINT.md).

Snapshot a running simulation at a conservative-round boundary into one
versioned archive; resume reconstructs a Manager mid-run whose continued
artifacts — packet traces, the four sim-time channels, sim-stats — are
byte-level continuations of a straight run.  The fault-injection harness
(host_kill / host_restore / link_down / nic_blackhole) rides the same
round-boundary choke point in the manager's loop.
"""

from shadow_tpu.ckpt.format import (CK_SEC_FAULTS, CK_SEC_HOSTS,
                                    CK_SEC_META, CK_SEC_NAMES,
                                    CK_SEC_PLANE, CK_SEC_RNG,
                                    CK_SEC_TRACE, CK_VERSION, CkptError,
                                    read_archive, read_meta,
                                    section_table, write_archive)
from shadow_tpu.ckpt.restore import (config_digest, restore_host,
                                     resume_manager)
from shadow_tpu.ckpt.snapshot import (checkpoint_domain_error,
                                      write_snapshot)

__all__ = [
    "CK_SEC_FAULTS", "CK_SEC_HOSTS", "CK_SEC_META", "CK_SEC_NAMES",
    "CK_SEC_PLANE", "CK_SEC_RNG", "CK_SEC_TRACE", "CK_VERSION",
    "CkptError", "checkpoint_domain_error", "config_digest",
    "read_archive", "read_meta", "restore_host", "resume_manager",
    "section_table", "write_archive", "write_snapshot",
]
