"""Rebuild live app-generator frames from their syscall transcripts.

Internal apps are Python generators driven through the syscall seam
(host/process.py) — a suspended generator frame cannot be pickled.
But the apps are written "like the C apps they stand in for": their
only inputs are the values the seam feeds back at each yield.  So a
thread's execution is a pure function of (app factory, argv, fed-value
sequence), and replaying the recorded sequence into a FRESH generator
reconstructs the exact suspension point — the record/replay trick rr
uses for real processes, applied at the syscall seam.

Recording (host/process.py Thread.resume, on when a `checkpoint:`
block is configured) logs one entry per generator interaction:
  (LOG_START,)        — first next()
  (LOG_SEND, value)   — result fed into gen.send
  (LOG_THROW, exc)    — OSError thrown into gen.throw
Replay feeds them back verbatim; the values yielded BY the generator
during replay are ignored except for `spawn_thread` yields, whose
factory callables are harvested to rebuild child threads (the recorded
send value of a spawn is the child's tid — the join key).
"""

from __future__ import annotations

from shadow_tpu.ckpt.format import CkptError

LOG_START = 0
LOG_SEND = 1
LOG_THROW = 2


def _replay_one(gen, log, factories: dict):
    """Feed a recorded transcript into a fresh generator.  Returns
    (gen, terminated): `terminated` when the generator finished or
    raised during replay (an exited thread's natural end)."""
    call = None
    try:
        for entry in log:
            kind = entry[0]
            if kind == LOG_START:
                call = next(gen)
            elif kind == LOG_SEND:
                if (isinstance(call, tuple) and call
                        and call[0] == "spawn_thread"):
                    # The recorded result of a spawn IS the child tid:
                    # harvest the factory for that thread's rebuild.
                    factories[entry[1]] = call[1]
                call = gen.send(entry[1])
            else:
                call = gen.throw(entry[1])
    except StopIteration:
        return gen, True
    except BaseException:
        # The final recorded feed made the app raise (thread crash /
        # ProcessExit): exactly how the original execution ended.
        return gen, True
    return gen, False


def rebuild_process(process) -> None:
    """Re-attach generator frames to every thread of one internal-app
    process after unpickling (threads are walked in spawn = tid order,
    so a parent's replay always harvests a child's factory before the
    child rebuilds)."""
    from shadow_tpu.host import apps as app_registry
    from shadow_tpu.host.process import ST_EXITED

    factories: dict = {}
    for i, t in enumerate(process.threads):
        if t.gen is not None:
            continue
        if i == 0:
            path = getattr(process, "app_path", None)
            factory = app_registry.lookup(path) if path else None
            if factory is None:
                raise CkptError(
                    f"cannot rebuild {process.name}: app "
                    f"{path!r} is not in the internal-app registry")
            gen = factory(process, process.argv)
        else:
            f = factories.pop(t.tid, None)
            if f is None:
                raise CkptError(
                    f"cannot rebuild {process.name} tid {t.tid}: no "
                    f"spawn_thread record in any parent transcript")
            gen = f() if callable(f) else f
        gen, terminated = _replay_one(gen, t.log or [], factories)
        if t.state == ST_EXITED and not terminated:
            # Killed mid-suspension (signal teardown): park the frame
            # closed, exactly as Thread._exit left the original.
            gen.close()
        elif t.state != ST_EXITED and terminated:
            raise CkptError(
                f"replay diverged for {process.name} tid {t.tid}: "
                f"transcript ended the generator but the thread was "
                f"recorded live (non-deterministic app?)")
        t.gen = gen


def rebuild_hosts(hosts) -> None:
    """Replay pass over every object-path host's internal-app
    processes (engine hosts carry no generator state)."""
    from shadow_tpu.host.process import Process
    for h in hosts:
        if h.plane is not None:
            continue
        for proc in h.processes.values():
            if type(proc) is Process:
                rebuild_process(proc)
