"""Snapshot a running simulation at a conservative-round boundary.

The manager's round loop calls `write_snapshot` at its boundary choke
point (core/manager.py); everything here is a read-only walk over
simulation state.  What goes in (docs/CHECKPOINT.md "what is
captured"): sim clock + round counters, every host's complete object
state (the Python object graph, with syscall transcripts standing in
for live generator frames — ckpt/replay.py), the C++ engine plane
(netplane.cpp plane_export), threefry RNG stream positions, the
event/inbox queues, the four sim-time trace channels' accumulated
bytes + counters, the eligibility audit, the object-lifecycle
counters, and the fault-schedule cursor.  Wall-side state (EWMAs,
phase walls, heartbeat cadence) is deliberately NOT captured — it is
stripped by the determinism gate and re-measured on resume.
"""

from __future__ import annotations

import json
import os
import pickle

from shadow_tpu.ckpt import format as ck


def checkpoint_domain_error(manager) -> str | None:
    """Why this simulation cannot be snapshotted (None = it can).
    The checkpoint domain is pure-sim hosts: engine hosts running
    engine-resident apps, and object-path hosts running internal
    (Python) apps under syscall-transcript recording.  Everything
    else is refused with a clear reason rather than silently dropped."""
    from shadow_tpu.host.engine_app import EngineAppProcess
    from shadow_tpu.host.managed import ManagedProcess
    exp = manager.config.experimental
    if exp.strace_logging_mode != "off":
        return ("strace logging is enabled: strace files stream to "
                "disk and cannot be resumed byte-identically "
                "(disable strace_logging_mode to checkpoint)")
    if exp.use_perf_timers:
        return "use_perf_timers is wall-clock state; disable it to checkpoint"
    # tpu_shards > 1 is IN the domain (ISSUE 11): shard layout never
    # reaches the archive bytes — the engine's plane_export and the
    # pickled host graphs are host-major canonical order, the sharded
    # outboxes are drained at every round boundary (write_snapshot
    # checks), and device-span residency is a cache over
    # engine-authoritative state.  A snapshot written single-shard may
    # resume sharded and vice versa (tpu_shards sits in the digest's
    # perf-knob skip list; gated in tests/test_ckpt.py).
    for name, hcfg in manager.config.hosts.items():
        if hcfg.pcap_enabled:
            return (f"host {name!r} captures pcap: capture files are "
                    f"append-only and cannot be resumed "
                    f"byte-identically (disable pcap to checkpoint)")
    # Managed (real-binary) processes snapshot under final-state-
    # checked RESTART semantics (ckpt/managed.py): restart records +
    # tombstoned runtime state, resumed runs gated on expected final
    # state instead of byte continuation.  Only live fork children
    # refuse (their lifecycle belongs to the parent's rerun).
    from shadow_tpu.ckpt.managed import managed_domain_error
    err = managed_domain_error(manager)
    if err is not None:
        return err
    for host in manager.hosts:
        if host.plane is not None:
            if host._nsocks:
                return (f"host {host.name!r} runs a Python process "
                        f"over engine sockets; move it off the plane "
                        f"(native_dataplane: false) or run it "
                        f"engine-resident to checkpoint")
            for proc in host.processes.values():
                if not isinstance(proc, EngineAppProcess):
                    return (f"{host.name}/{proc.name}: only engine-"
                            f"resident apps are snapshottable on "
                            f"plane hosts")
        else:
            for proc in host.processes.values():
                if isinstance(proc, ManagedProcess):
                    continue  # restart records, not transcripts
                for t in getattr(proc, "threads", ()):
                    from shadow_tpu.host.process import ST_EXITED
                    if t.state != ST_EXITED and t.log is None:
                        return (f"{host.name}/{proc.name}: live app "
                                f"thread without a syscall transcript "
                                f"— checkpointing must be enabled "
                                f"from simulation start (a "
                                f"`checkpoint:` config block turns "
                                f"recording on)")
    return None


def _trace_state(manager) -> dict:
    """The sim-time channels' continuation state: accumulated bytes +
    record/drop counters, plus the always-on audit and the
    object-lifecycle counters (both land in byte-diffed sim-stats)."""
    from shadow_tpu.utils import object_counter
    out: dict = {
        "audit": list(manager.audit.counts),
        "objects": (dict(object_counter._alloc),
                    dict(object_counter._dealloc)),
    }
    flight = manager.flight
    if flight is not None and flight.sim is not None:
        s = flight.sim
        out["flight_sim"] = (s.to_bytes(), s.records, s.dropped)
    for name in ("netstat", "fabric", "kern"):
        ch = getattr(manager, name)
        if ch is not None:
            out[name] = (ch.to_bytes(), ch.records, ch.dropped)
    sct = manager.sctrace
    if sct is not None and sct.channel is not None:
        out["sctrace"] = [(b"".join(log.chunks), log.records,
                           log.dropped) for log in sct.channel._logs]
    return out


def _fault_state(manager) -> dict:
    return {
        "applied": getattr(manager, "_faults_applied", 0),
        "hosts": {h.id: [bool(getattr(h, "down", False)),
                         bool(getattr(h, "link_down", False)),
                         bool(getattr(h, "blackhole", False))]
                  for h in manager.hosts
                  if getattr(h, "down", False)
                  or getattr(h, "link_down", False)
                  or getattr(h, "blackhole", False)},
    }


def write_snapshot(manager, summary, next_start: int, path: str,
                   live: dict | None = None) -> dict:
    """Serialize the simulation at the current round boundary into
    `path`.  `summary` is the in-progress SimSummary (round counters);
    `next_start` the boundary's next window start; `live` carries the
    deterministic router counters (dev_span_K ladder) the resumed loop
    re-seeds.  Returns the meta dict."""
    from shadow_tpu.ckpt.restore import config_digest
    err = checkpoint_domain_error(manager)
    if err is not None:
        raise ck.CkptError(f"cannot snapshot: {err}")
    if getattr(manager.propagator, "_outbox", None):
        raise ck.CkptError("cannot snapshot: propagator outbox not "
                           "drained at this boundary")
    sections: dict[int, bytes] = {}

    engine = None
    if manager.plane is not None:
        engine = manager.plane.engine
        sections[ck.CK_SEC_PLANE] = engine.plane_export()

    # Managed processes: build restart records and pickle the host
    # graph through the tombstone-stripping pickler (ckpt/managed.py)
    # — read-only over the live run either way.
    from shadow_tpu.ckpt.managed import collect_managed, dumps_hosts
    managed_records, owned_ids = collect_managed(manager)
    if managed_records:
        sections[ck.CK_SEC_MANAGED] = pickle.dumps(managed_records,
                                                   protocol=4)
    try:
        if owned_ids or managed_records:
            sections[ck.CK_SEC_HOSTS] = dumps_hosts(manager.hosts,
                                                    owned_ids)
        else:
            sections[ck.CK_SEC_HOSTS] = pickle.dumps(manager.hosts,
                                                     protocol=4)
    except Exception as e:
        raise ck.CkptError(
            f"cannot snapshot: host state holds an unserializable "
            f"object ({e!r}) — epoll/futex waiters and other "
            f"managed-process machinery are outside the checkpoint "
            f"domain (docs/CHECKPOINT.md)") from e

    sections[ck.CK_SEC_RNG] = ck.pack_rng_rows(
        [(h.id, h.rng._counter) for h in manager.hosts
         if h.plane is None])
    sections[ck.CK_SEC_TRACE] = pickle.dumps(_trace_state(manager),
                                             protocol=4)
    sections[ck.CK_SEC_FAULTS] = json.dumps(
        _fault_state(manager), sort_keys=True).encode()

    meta = {
        "ck_version": ck.CK_VERSION,
        "config_digest": config_digest(manager.config),
        "seed": manager.config.general.seed,
        "stop_time_ns": manager.config.general.stop_time_ns,
        "n_hosts": len(manager.hosts),
        "engine": manager.plane is not None,
        # Managed restart records in the archive (0 = pure-sim
        # snapshot with the full byte-continuation contract; >0 =
        # resume restarts these binaries fresh under final-state
        # gating, docs/CHECKPOINT.md "Managed processes").
        "managed": len(managed_records),
        "rounds": summary.rounds,
        "span_rounds": summary.span_rounds,
        "busy_end_ns": summary.busy_end_ns,
        "next_start_ns": int(next_start),
        "runahead_ns": manager.runahead.get(),
        "faults_applied": getattr(manager, "_faults_applied", 0),
        "live": dict(live or {}),
        "channels": {
            "flight_recorder":
                manager.config.experimental.flight_recorder,
            "sim_netstat": manager.config.experimental.sim_netstat,
            "sim_fabricstat":
                manager.config.experimental.sim_fabricstat,
            "syscall_observatory":
                manager.config.experimental.syscall_observatory,
            "kernel_observatory":
                manager.config.experimental.kernel_observatory,
        },
    }
    sections[ck.CK_SEC_META] = json.dumps(meta, sort_keys=True).encode()

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    ck.write_archive(path, sections)
    return meta
