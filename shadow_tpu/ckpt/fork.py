"""`tools/ckpt fork`: clone one post-ramp snapshot into N
config-variant resume points (docs/CHECKPOINT.md "Fork", docs/SWEEP.md
"Warm starts").

A snapshot resumes only under a config whose simulation-semantic
digest matches (ckpt/restore.config_digest) — the right default, but
it forbids exactly the thing a sim farm wants: snapshot ONCE past
ramp, then resume N parameter variants from the same warm state
(ROADMAP item 5).  Fork is the explicit, allowlisted escape hatch: it
re-stamps the archive's config digest for a variant config that
differs from the snapshot's ONLY in FORK-SAFE knobs — options that
shape FUTURE simulation behavior but are never encoded in snapshotted
state, so the archive's bytes mean exactly the same thing under the
variant:

- ``experimental.dctcp_k_pkts`` / ``dctcp_k_bytes``: the marking law
  reads K at enqueue time from config (engine-global / host attr /
  kernel closure — never serialized), so a forked archive marks under
  the variant's K from the first post-fork round.
- ``general.stop_time``: nothing in the archive depends on when the
  sim will END (the snapshot predates it); the fork refuses a variant
  whose stop_time is not strictly after the snapshot boundary.

Everything else is refused with the offending key paths named.  In
particular per-host ``tcp: {cc, ecn}`` changes are refused with their
own message: cc/ECN state is baked into every live connection in the
archive (c_cc, alpha, latches), so a cc variant is NOT byte-compatible
— run that point cold.

The forked file is a byte-faithful clone except for the meta section
(new config digest), so `ckpt verify` passes and resume applies every
gate it normally would.
"""

from __future__ import annotations

import json
import os

from shadow_tpu.ckpt import format as ck
from shadow_tpu.ckpt.format import CkptError
from shadow_tpu.ckpt.restore import (_DIGEST_SKIP_EXPERIMENTAL,
                                     _DIGEST_SKIP_GENERAL,
                                     config_digest)

# The fork-safe allowlist (see module docstring).  Keys already
# excluded from the digest (_DIGEST_SKIP_*, the checkpoint schedule)
# may differ freely — they were never part of the compatibility
# contract to begin with.
FORK_SAFE_GENERAL = ("stop_time",)
FORK_SAFE_EXPERIMENTAL = ("dctcp_k_pkts", "dctcp_k_bytes")
# `faults:` schedules are fork-safe with two structural conditions
# checked in fork_archive (ROADMAP item 5 — fault-variant fleets from
# one warm snapshot): (1) the prefix the snapshot already APPLIED must
# be preserved verbatim — the archive's fault cursor indexes into the
# variant's schedule, and the per-host fault flags in the archive mean
# "these ops happened"; (2) every other op must land strictly AFTER
# the fork boundary — an op at or before it could never apply (the
# round loop is already past) and would silently diverge from what
# the archive claims, so it is refused instead.


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for k in sorted(d):
        v = d[k]
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, path + "."))
        else:
            out[path] = v
    return out


def fork_diff(base_config, variant_config) -> list[str]:
    """The key paths where the two processed configs differ, with the
    digest-irrelevant keys (skip lists + checkpoint schedule) already
    removed.  Empty list = identical digests."""
    def semantic(config):
        d = config.to_processed_dict()
        g = d.get("general", {})
        for k in _DIGEST_SKIP_GENERAL:
            g.pop(k, None)
        e = d.get("experimental", {})
        for k in _DIGEST_SKIP_EXPERIMENTAL:
            e.pop(k, None)
        d.pop("checkpoint", None)
        return _flatten(d)

    a, b = semantic(base_config), semantic(variant_config)
    return sorted(p for p in set(a) | set(b) if a.get(p) != b.get(p))


def check_fork_compatible(base_config, variant_config) -> list[str]:
    """Raise CkptError unless the variant differs from the base only
    in fork-safe knobs; returns the (possibly empty) list of differing
    fork-safe key paths."""
    allowed = {f"general.{k}" for k in FORK_SAFE_GENERAL} \
        | {f"experimental.{k}" for k in FORK_SAFE_EXPERIMENTAL}
    diffs = fork_diff(base_config, variant_config)
    # faults: the whole schedule flattens under the "faults" prefix
    # (a list — _flatten keeps it one leaf); structural validity is
    # checked against the archive in fork_archive.
    bad = [p for p in diffs
           if p not in allowed and p.split(".")[0] != "faults"]
    if bad:
        tcp_bad = [p for p in bad
                   if p.startswith("hosts.") and ".tcp" in p]
        if tcp_bad:
            raise CkptError(
                f"fork refused: per-host tcp (cc/ecn) changes are not "
                f"byte-compatible — cc state (alpha, latches, c_cc) "
                f"is baked into every live connection in the archive; "
                f"run that variant cold ({', '.join(tcp_bad[:4])})")
        raise CkptError(
            f"fork refused: variant config differs outside the "
            f"fork-safe knobs ({', '.join(bad[:6])}"
            f"{', …' if len(bad) > 6 else ''}); fork-safe: "
            f"{', '.join(sorted(allowed))}")
    return diffs


def _check_fault_fork(base_config, variant_config, meta: dict) -> None:
    """Structural validity of a fault-schedule fork against the
    archive (see the FORK_SAFE comment): applied prefix preserved,
    every other op strictly after the fork boundary."""
    applied = int(meta.get("faults_applied", 0))
    boundary = int(meta["next_start_ns"])
    base = list(base_config.faults or ())
    variant = list(variant_config.faults or ())
    if len(variant) < applied:
        raise CkptError(
            f"fork refused: the snapshot already applied {applied} "
            f"fault op(s) but the variant schedule has only "
            f"{len(variant)} — the applied prefix must be preserved")

    def row(f):
        return (f.at_ns, f.action, f.host,
                getattr(f, "snapshot", None))

    for i in range(applied):
        if row(variant[i]) != row(base[i]):
            raise CkptError(
                f"fork refused: fault op {i} was already applied by "
                f"the snapshot and must be preserved verbatim in the "
                f"variant (the archive's fault flags and cursor mean "
                f"exactly those ops happened)")
    for i in range(applied, len(variant)):
        if variant[i].at_ns <= boundary:
            raise CkptError(
                f"fork refused: variant fault op {i} "
                f"({variant[i].action} {variant[i].host} at "
                f"{variant[i].at_ns} ns) is at or before the fork "
                f"boundary ({boundary} ns) — the resumed round loop "
                f"is already past it, so it could never apply; "
                f"schedule fault variants strictly after the boundary")


def fork_archive(snapshot_path: str, base_config, variant_config,
                 out_path: str) -> list[str]:
    """Clone `snapshot_path` (taken under `base_config`) into a resume
    point for `variant_config`.  Returns the forked key paths.  The
    output archive is identical except for meta.config_digest."""
    sections = ck.read_archive(snapshot_path)
    meta = json.loads(sections[ck.CK_SEC_META].decode())
    base_digest = config_digest(base_config)
    if meta["config_digest"] != base_digest:
        raise CkptError(
            f"{snapshot_path}: snapshot was not taken under the given "
            f"base config (digest mismatch) — fork needs the ORIGINAL "
            f"config to prove the variant differs only in fork-safe "
            f"knobs")
    diffs = check_fork_compatible(base_config, variant_config)
    stop_ns = variant_config.general.stop_time_ns
    if stop_ns and stop_ns <= meta["next_start_ns"]:
        raise CkptError(
            f"fork refused: variant stop_time ({stop_ns} ns) is not "
            f"after the snapshot boundary ({meta['next_start_ns']} "
            f"ns) — nothing would run")
    if any(p.split(".")[0] == "faults" for p in diffs):
        _check_fault_fork(base_config, variant_config, meta)
    meta["config_digest"] = config_digest(variant_config)
    meta["forked_from"] = os.path.basename(snapshot_path)
    meta["forked_keys"] = diffs
    sections = dict(sections)
    sections[ck.CK_SEC_META] = json.dumps(meta, sort_keys=True).encode()
    ck.write_archive(out_path, sections)
    return diffs
