"""Resume a Manager mid-run from a snapshot archive.

Restore is rebuild-then-overwrite: a fresh Manager is constructed from
the (digest-checked) config — hosts, routing matrices, engine plane,
propagator, channels all in their start-of-run shape — and the
snapshot's mutable state is imported over it: the engine via
plane_import (netplane.cpp), the Python object graph via the pickled
hosts list (generator frames rebuilt by ckpt/replay.py), the trace
channels/audit/object-counters from the trace section.  The round loop
then continues from `meta.next_start_ns`; every byte-diffed artifact
is a continuation of the straight run's (the tier-1 gate in
tests/test_ckpt.py is the proof).
"""

from __future__ import annotations

import hashlib
import json
import pickle

from shadow_tpu.ckpt import format as ck
from shadow_tpu.ckpt.format import CkptError

# Config keys with no bearing on simulation bytes: two runs differing
# only here may share snapshots (the scheduler/path split is checked
# separately via meta.engine, with a clearer error than a hash).
_DIGEST_SKIP_GENERAL = ("data_directory", "progress", "log_level",
                        "parallelism", "heartbeat_interval")
_DIGEST_SKIP_EXPERIMENTAL = (
    "scheduler", "use_cpu_pinning", "native_dataplane",
    "tpu_device_spans", "tpu_min_device_batch",
    "tpu_max_packets_per_round", "tpu_shards", "tpu_exchange_capacity",
    "pcap_span_cap", "chrome_top_n", "report_errors_to_stderr",
    "tpu_donate_buffers",
    # Syscall service plane: a wall-side scheduling knob (byte
    # identity holds on and off — tests/test_svc.py) and the waitpid
    # safety-net poll slice, which never reaches simulation bytes.
    "syscall_service_plane", "managed_death_poll",
    # Failure-containment wall knobs (docs/ROBUSTNESS.md): the hang
    # watchdog and the spawn stagger shape WALL behavior only — a
    # contained failure's sim-side effects are pinned by the fault
    # ledger, never by these.
    "managed_watchdog", "managed_spawn_stagger",
    # Overlapped span pipeline (ISSUE 16): dispatch scheduling and
    # window-sizing knobs are wall-side routing only — byte identity
    # on/off is gated in tests/test_overlap.py, and the pallas queue
    # kernels are integer-exact twins of the inline lax forms.
    "span_overlap", "pallas_queue_kernels",
    "dev_span_k_init", "dev_span_k_floor", "dev_span_k_shrink",
)


def config_digest(config) -> str:
    """Hash of the simulation-semantic slice of the processed config:
    a snapshot resumes only under a config that would have produced
    the same simulation bytes (path/wall knobs excluded)."""
    d = config.to_processed_dict()
    g = d.get("general", {})
    for k in _DIGEST_SKIP_GENERAL:
        g.pop(k, None)
    e = d.get("experimental", {})
    for k in _DIGEST_SKIP_EXPERIMENTAL:
        e.pop(k, None)
    # Future checkpoint schedules may differ freely; the FAULT schedule
    # is semantic (it shapes simulation bytes) and stays in the hash.
    d.pop("checkpoint", None)
    return hashlib.sha256(
        json.dumps(d, sort_keys=True).encode()).hexdigest()


def _load_channel(ch, state) -> None:
    data, records, dropped = state
    ch._chunks = [data] if data else []
    ch.records = records
    ch.dropped = dropped


def _restore_trace(manager, tr: dict) -> None:
    from shadow_tpu.utils import object_counter
    if len(tr["audit"]) != len(manager.audit.counts):
        raise CkptError("snapshot audit table width differs "
                        "(EL_* reason set changed between builds)")
    manager.audit.counts[:] = tr["audit"]
    alloc, dealloc = tr["objects"]
    with object_counter._lock:
        object_counter._alloc.clear()
        object_counter._alloc.update(alloc)
        object_counter._dealloc.clear()
        object_counter._dealloc.update(dealloc)

    def channel_or_raise(obj, name):
        if obj is None:
            raise CkptError(
                f"snapshot carries {name} channel state but the "
                f"resumed config does not enable it — keep the "
                f"observability knobs identical to resume")
        return obj

    if "flight_sim" in tr:
        flight = manager.flight
        sim = flight.sim if flight is not None else None
        _load_channel(channel_or_raise(sim, "flight-recorder sim"),
                      tr["flight_sim"])
    if "netstat" in tr:
        _load_channel(channel_or_raise(manager.netstat, "sim-netstat"),
                      tr["netstat"])
    if "fabric" in tr:
        _load_channel(channel_or_raise(manager.fabric, "fabric"),
                      tr["fabric"])
    if "kern" in tr:
        _load_channel(channel_or_raise(manager.kern, "device-kernel"),
                      tr["kern"])
    if "sctrace" in tr:
        sct = manager.sctrace
        chan = sct.channel if sct is not None else None
        chan = channel_or_raise(chan, "syscall")
        if len(chan._logs) != len(tr["sctrace"]):
            raise CkptError("snapshot syscall-log count differs from "
                            "the rebuilt host set")
        for log, (data, records, dropped) in zip(chan._logs,
                                                 tr["sctrace"]):
            log.chunks = [data] if data else []
            log.records = records
            log.dropped = dropped


def _rewire(manager, h, fresh, appmap: dict) -> None:
    """Re-attach the manager-owned references a pickled Host
    deliberately drops (Host.__getstate__), using the fresh twin the
    rebuilt Manager made for the same id."""
    h.dns = manager.dns
    h.syscall_handler = manager.syscall_handler
    h.syscall_handler_native = manager.syscall_handler_native
    # DCTCP-K is config, not state: the RESUMED config's values govern
    # (the seam tools/ckpt fork relies on — a forked archive resumes
    # under the variant's K from the first post-fork round).
    h.dctcp_k_pkts = fresh.dctcp_k_pkts
    h.dctcp_k_bytes = fresh.dctcp_k_bytes
    # Same rule for the service-plane knobs (all digest-skipped, so a
    # resume may legitimately change them): the waitpid safety-net
    # poll slice and the svc advertisement come from the RESUMED
    # config, not the archive — otherwise the pickled values would
    # silently override while metrics.wall.ipc reported the new ones.
    h.death_poll_ns = fresh.death_poll_ns
    h.svc_managed = fresh.svc_managed
    h.py_pinned = fresh.py_pinned
    # Failure containment (docs/ROBUSTNESS.md): the plane and the
    # wall-only spawn stagger are manager-owned / wall-side — the
    # RESUMING config's values govern.
    h.containment = getattr(fresh, "containment", None)
    h.spawn_stagger_ns = getattr(fresh, "spawn_stagger_ns", 0)
    h.svc_active = getattr(fresh, "svc_active", False)
    h.data_path = fresh.data_path
    h.strace_mode = getattr(fresh, "strace_mode", None)
    h._send_packet_fn = manager.propagator.send
    if fresh.plane is not None:
        h.plane = fresh.plane
        h.rng.attach_engine(fresh.plane.engine, h.id)
        for proc in h.processes.values():
            old = getattr(proc, "app_idx", None)
            if old is not None:
                try:
                    proc.app_idx = appmap[old]
                except KeyError:
                    raise CkptError(
                        f"{h.name}/{proc.name}: engine app {old} "
                        f"missing from the imported plane") from None
    if manager.sctrace is not None:
        h.sc_wall = fresh.sc_wall
        h.sc_log = fresh.sc_log
    # In-flight cross-host deliveries were snapshotted in the locked
    # inbox staging deque; fold them into the heap now so the resumed
    # _init_next_times sees them (live runs maintain the shared
    # next-event slot incrementally instead).
    h.drain_inbox()


def _check_meta(config, meta: dict, want_engine: bool) -> None:
    if meta["ck_version"] != ck.CK_VERSION:
        raise CkptError(f"snapshot meta version {meta['ck_version']} "
                        f"!= supported {ck.CK_VERSION}")
    digest = config_digest(config)
    if digest != meta["config_digest"]:
        raise CkptError(
            "config does not match the snapshot (simulation-semantic "
            "options differ — seed, topology, hosts, buffers, or the "
            "fault schedule changed since the snapshot was written)")
    if want_engine != meta["engine"]:
        took = "engine" if meta["engine"] else "object"
        need = ("scheduler: tpu (or engine-backed thread_per_core)"
                if meta["engine"] else
                "an object-path scheduler (serial / thread_per_core)")
        raise CkptError(
            f"snapshot was taken on the {took} path; resume it with "
            f"{need} — cross-plane state conversion is not supported")


def resume_manager(config, path: str):
    """Rebuild a Manager from `config` and restore the snapshot at
    `path` over it.  The returned manager's run() continues the
    simulation from the snapshot boundary."""
    from shadow_tpu.ckpt import replay
    from shadow_tpu.core.manager import Manager

    sections = ck.read_archive(path)
    meta = json.loads(sections[ck.CK_SEC_META].decode())
    manager = Manager(config)
    _check_meta(config, meta, manager.plane is not None)
    if len(manager.hosts) != meta["n_hosts"]:
        raise CkptError(f"snapshot has {meta['n_hosts']} hosts, "
                        f"config builds {len(manager.hosts)}")

    appmap: dict = {}
    if manager.plane is not None:
        appmap = manager.plane.engine.plane_import(
            sections[ck.CK_SEC_PLANE])

    managed_records = None
    if ck.CK_SEC_MANAGED in sections:
        managed_records = pickle.loads(sections[ck.CK_SEC_MANAGED])

    hosts = pickle.loads(sections[ck.CK_SEC_HOSTS])
    if len(hosts) != len(manager.hosts):
        raise CkptError("snapshot host list does not match the config")
    for h in hosts:
        fresh = manager.hosts[h.id]
        if fresh.name != h.name:
            raise CkptError(f"host order mismatch: {fresh.name!r} vs "
                            f"snapshot {h.name!r}")
        if managed_records is not None:
            # Managed restart semantics: sweep the tombstoned managed
            # machinery out BEFORE anything walks the host (processes,
            # no-op heap tasks, dead socket associations) — the
            # restart records below re-create the fleet.
            from shadow_tpu.ckpt.managed import purge_tombstones
            purge_tombstones(h)
        _rewire(manager, h, fresh, appmap)
        manager.hosts[h.id] = h
    replay.rebuild_hosts(manager.hosts)

    _restore_trace(manager, pickle.loads(sections[ck.CK_SEC_TRACE]))

    # The RNG and fault sections are what `ckpt diff` renders; the
    # authoritative copies travel in the host pickle / plane blob.
    # Cross-check them so the two representations can never silently
    # disagree (a mismatch means a corrupt or hand-edited archive).
    rng_rows = dict(ck.iter_rng_rows(sections[ck.CK_SEC_RNG]))
    for h in manager.hosts:
        if h.plane is None and rng_rows.get(h.id) != h.rng._counter:
            raise CkptError(
                f"rng section disagrees with host {h.name!r} state "
                f"({rng_rows.get(h.id)} vs {h.rng._counter}) — "
                f"corrupt archive")
    faults = json.loads(sections[ck.CK_SEC_FAULTS].decode())
    for hid_s, flags in faults.get("hosts", {}).items():
        h = manager.hosts[int(hid_s)]
        live = [bool(getattr(h, "down", False)),
                bool(getattr(h, "link_down", False)),
                bool(getattr(h, "blackhole", False))]
        if live != list(flags):
            raise CkptError(
                f"fault section disagrees with host {h.name!r} "
                f"state — corrupt archive")
    manager._faults_applied = int(faults.get("applied", 0))
    manager.runahead._value = max(1, int(meta["runahead_ns"]))
    if managed_records is not None:
        # Restart the managed fleet at the boundary: exited processes
        # come back as final-state husks, live ones respawn fresh and
        # the run is gated on their recorded expected final state
        # (no byte-continuation contract for managed traffic —
        # docs/CHECKPOINT.md "Managed processes").
        from shadow_tpu.ckpt.managed import restore_managed
        restore_managed(manager, managed_records,
                        meta["next_start_ns"])
    manager._resume = {
        "rounds": meta["rounds"],
        "span_rounds": meta["span_rounds"],
        "busy_end_ns": meta["busy_end_ns"],
        "next_start_ns": meta["next_start_ns"],
        "live": meta.get("live", {}),
        "path": path,
    }
    return manager


def restore_host(manager, path: str, host_id: int, at: int) -> None:
    """The host_restore fault: mid-run, re-import ONE host's state
    from a snapshot taken earlier in this run (both planes), bumping
    its past-due event times to the current boundary `at`.  The
    host's counters and trace roll back to snapshot values with it —
    the semantics of a node recovering from its last backup."""
    from shadow_tpu.ckpt import replay
    from shadow_tpu.host.process import Process

    sections = ck.read_archive(path)
    meta = json.loads(sections[ck.CK_SEC_META].decode())
    _check_meta(manager.config, meta, manager.plane is not None)
    if meta.get("managed"):
        raise CkptError(
            "host_restore from a snapshot carrying managed restart "
            "records is not supported — a managed process cannot be "
            "re-imaged mid-run (docs/CHECKPOINT.md)")

    cur = manager.hosts[host_id]
    appmap: dict = {}
    if cur.plane is not None:
        appmap = manager.plane.engine.host_import(
            sections[ck.CK_SEC_PLANE], host_id, at)

    hosts = pickle.loads(sections[ck.CK_SEC_HOSTS])
    h = hosts[host_id]
    if h.id != host_id:
        raise CkptError("snapshot host list is not id-ordered")
    _rewire(manager, h, cur, appmap)
    if h.plane is None:
        # Object path: bump past-due Python event times to the
        # boundary (stable: bumped events tie on time and keep their
        # (kind, src, seq) order), then rebuild generator frames.
        import heapq
        heap = h.queue._heap
        bumped = [(max(t, at), k, s, q, ev) for (t, k, s, q, ev)
                  in heap]
        for (t, k, s, q, ev) in bumped:
            ev.time = t
        heapq.heapify(bumped)
        h.queue._heap = bumped
        h.queue._last_popped_time = 0
        from shadow_tpu.core.simtime import TIME_NEVER
        with h._inbox_lock:
            for ev in h._inbox:
                if ev.time < at:
                    ev.time = at
            h._inbox_min = min((ev.time for ev in h._inbox),
                               default=TIME_NEVER)
        if h._now < at:
            h._now = at
        for proc in h.processes.values():
            if type(proc) is Process:
                replay.rebuild_process(proc)
    manager.hosts[host_id] = h
    # Drop back into the live scheduling structures.
    h._nt_list = manager._nt if len(manager._nt) else None
    h._py_work_arr = (manager._py_work
                      if getattr(manager, "_py_work", None) is not None
                      and h.plane is not None else None)
    if h._nt_list is not None:
        h._update_nt_slot()
    # The restored flags govern; mirror them engine-side.
    if h.plane is not None:
        manager.plane.engine.set_host_fault(
            host_id, bool(getattr(h, "down", False)),
            bool(getattr(h, "link_down", False)),
            bool(getattr(h, "blackhole", False)))
