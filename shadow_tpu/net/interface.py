"""Network interfaces: socket association, send queueing, recv demux.

Ref: src/main/host/network/interface.rs + queuing.rs + namespace.rs.
Each host has `lo` (127.0.0.1) and `eth0` (its public IP). Outbound,
sockets with pending packets wait in a qdisc-ordered queue that the
upload relay drains; inbound, packets demux to the owning socket by
(protocol, local, peer) with wildcard-peer fallback — the same two-level
lookup the reference uses.
"""

from __future__ import annotations

import heapq
from collections import deque

from shadow_tpu.net import packet as pkt

QDISC_FIFO = "fifo"
QDISC_ROUND_ROBIN = "round_robin"


class NetworkInterface:
    __slots__ = ("ip", "name", "qdisc", "_assoc", "_port_use",
                 "_send_ready", "_send_heap",
                 "_queued", "pcap", "packets_sent", "packets_received",
                 "bytes_sent", "bytes_received")

    def __init__(self, ip: int, name: str, qdisc: str = QDISC_FIFO):
        self.ip = ip
        self.name = name
        self.qdisc = qdisc
        # (proto, local_ip, local_port, peer_ip, peer_port) -> socket.
        # Wildcard peer is (0, 0).
        self._assoc: dict = {}
        # (proto, local_port) -> live association count (wildcard AND
        # 4-tuple).  The ephemeral-port picker consults this: a port
        # whose old connection is still tearing down (FIN/TIME_WAIT
        # holds a 4-tuple assoc) must not be handed out again — reuse
        # against the same peer collides the 4-tuple.
        self._port_use: dict = {}
        self._send_ready: deque = deque()  # round-robin order
        self._send_heap: list = []         # fifo order by packet priority
        self._queued: set = set()          # sockets currently queued
        self.pcap = None                   # PcapWriter hook (utils/pcap.py)
        self.packets_sent = 0
        self.packets_received = 0
        self.bytes_sent = 0
        self.bytes_received = 0

    # ------------------------------------------------------------------
    # Checkpoint serialization (shadow_tpu/ckpt/): the send structures
    # carry id(socket) heap tiebreaks (never consulted — packet
    # priorities are unique) and a membership set whose iteration
    # order is address-derived.  Both would make snapshot bytes differ
    # between identical runs, so the pickle form canonicalizes them:
    # tiebreaks become heap-array indices, the set becomes a list in
    # deterministic (heap-array + round-robin) order.
    # ------------------------------------------------------------------

    def __getstate__(self):
        d = {k: getattr(self, k) for k in self.__slots__
             if hasattr(self, k)}
        d["_send_heap"] = [(prio, i, sock) for i, (prio, _t, sock)
                           in enumerate(self._send_heap)]
        queued = []
        for sock in [s for (_p, _t, s) in self._send_heap] + \
                list(self._send_ready):
            if sock in self._queued and sock not in queued:
                queued.append(sock)
        d["_queued"] = queued
        return d

    def __setstate__(self, d):
        queued = d.pop("_queued")
        for k, v in d.items():
            setattr(self, k, v)
        self._queued = set(queued)

    # ------------------------------------------------------------------
    # Associations (namespace.rs: bind-time registration)
    # ------------------------------------------------------------------

    def associate(self, socket, proto: int, local_port: int,
                  peer_ip: int = 0, peer_port: int = 0) -> None:
        key = (proto, self.ip, local_port, peer_ip, peer_port)
        if key in self._assoc:
            import errno
            raise OSError(errno.EADDRINUSE, "address already in use")
        self._assoc[key] = socket
        pk = (proto, local_port)
        self._port_use[pk] = self._port_use.get(pk, 0) + 1

    def disassociate(self, proto: int, local_port: int,
                     peer_ip: int = 0, peer_port: int = 0) -> None:
        key = (proto, self.ip, local_port, peer_ip, peer_port)
        if self._assoc.pop(key, None) is not None:
            pk = (proto, local_port)
            n = self._port_use.get(pk, 0) - 1
            if n <= 0:
                self._port_use.pop(pk, None)
            else:
                self._port_use[pk] = n

    def is_associated(self, proto: int, local_port: int,
                      peer_ip: int = 0, peer_port: int = 0) -> bool:
        return (proto, self.ip, local_port, peer_ip, peer_port) in self._assoc

    def port_in_use(self, proto: int, local_port: int) -> bool:
        """Any live association (wildcard or 4-tuple) on this port."""
        return (proto, local_port) in self._port_use

    def lookup(self, proto: int, local_port: int, peer_ip: int,
               peer_port: int):
        """Connection-specific association first, then wildcard listener."""
        s = self._assoc.get((proto, self.ip, local_port, peer_ip, peer_port))
        if s is None:
            s = self._assoc.get((proto, self.ip, local_port, 0, 0))
        return s

    def associated_sockets(self, proto: int | None = None):
        """Every associated socket, in association-key order (the
        sim-netstat walker re-sorts by connection identity, but a
        deterministic base order keeps dict-insertion history out of
        the stream)."""
        for key in sorted(self._assoc):
            if proto is None or key[0] == proto:
                yield self._assoc[key]

    # ------------------------------------------------------------------
    # Send path (interface.rs:57-119, queuing.rs NetworkQueue)
    # ------------------------------------------------------------------

    def notify_socket_has_packets(self, host, socket) -> None:
        if socket in self._queued:
            return
        if socket.peek_next_packet_priority(self) is None:
            return
        self._queued.add(socket)
        if self.qdisc == QDISC_ROUND_ROBIN:
            self._send_ready.append(socket)
        else:
            heapq.heappush(self._send_heap,
                           (socket.peek_next_packet_priority(self),
                            id(socket), socket))
        # Kick the relay that drains this interface.
        host.notify_interface_has_packets(self)

    def pop_packet(self, host, now: int):
        """Called by the upload/loopback relay to pull the next packet."""
        while True:
            socket = self._next_queued_socket()
            if socket is None:
                return None
            packet = socket.pull_out_packet(host, self)
            # Re-queue the socket if it still has packets.
            if socket.peek_next_packet_priority(self) is not None:
                self._queued.add(socket)
                if self.qdisc == QDISC_ROUND_ROBIN:
                    self._send_ready.append(socket)
                else:
                    heapq.heappush(self._send_heap,
                                   (socket.peek_next_packet_priority(self),
                                    id(socket), socket))
            if packet is not None:
                self.packets_sent += 1
                self.bytes_sent += packet.total_size()
                if self.pcap is not None:
                    self.pcap.write_packet(now, packet)
                host.trace_snd(packet)
                return packet

    def _next_queued_socket(self):
        if self.qdisc == QDISC_ROUND_ROBIN:
            while self._send_ready:
                s = self._send_ready.popleft()
                if s in self._queued:
                    self._queued.discard(s)
                    return s
            return None
        while self._send_heap:
            _, _, s = heapq.heappop(self._send_heap)
            if s in self._queued:
                self._queued.discard(s)
                return s
        return None

    # ------------------------------------------------------------------
    # Receive path
    # ------------------------------------------------------------------

    def push(self, host, packet) -> None:
        """Inbound delivery from a relay (PacketDevice::push)."""
        now = host.now()
        packet.record(pkt.ST_RCV_INTERFACE)
        self.packets_received += 1
        self.bytes_received += packet.total_size()
        if self.pcap is not None:
            self.pcap.write_packet(now, packet)
        socket = self.lookup(packet.protocol, packet.dst_port,
                             packet.src_ip, packet.src_port)
        if socket is None:
            # No receiver: the packet vanishes (a RST/ICMP refinement can
            # hook here later, matching legacy_tcp behavior).
            host.trace_drop(packet, "no-socket")
            return
        if socket.push_in_packet(host, packet):
            packet.record(pkt.ST_RCV_DELIVERED)
            host.trace_rcv(packet)


def check_bind_port(ifaces, proto: int, port: int,
                    reuseaddr: bool) -> None:
    """Shared explicit-port bind check (TCP + UDP sockets): without
    SO_REUSEADDR, Linux refuses a port with ANY live association —
    TIME_WAIT 4-tuples included; with it, only an exact wildcard
    collision blocks (the server-restart pattern).  Twin:
    netplane.cpp generic_bind."""
    import errno
    for iface in ifaces:
        if (iface.port_in_use(proto, port) if not reuseaddr
                else iface.is_associated(proto, port)):
            raise OSError(errno.EADDRINUSE, "address already in use")
