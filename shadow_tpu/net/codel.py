"""CoDel active queue management (RFC 8289) for the per-host router.

Own implementation of the RFC algorithm with the Linux/reference
parameters (src/main/network/router/codel_queue.rs: TARGET 5ms,
INTERVAL 100ms, hard cap 1000 packets). All arithmetic is integer
nanoseconds; the control law's inverse-sqrt is computed with integer
math so the CPU and any future vectorized implementation agree bit-forr-bit.
"""

from __future__ import annotations

from collections import deque
from math import isqrt

from shadow_tpu.net import packet as pkt
from shadow_tpu.trace.events import MARK_THRESH_BYTES, MARK_THRESH_PKTS

TARGET_NS = 5_000_000       # 5 ms acceptable standing delay
INTERVAL_NS = 100_000_000   # 100 ms sliding window
HARD_LIMIT = 1000           # max queued packets (codel_queue.rs limit)

# DCTCP instantaneous marking threshold K (RFC 8257 4.1; netplane.cpp
# CoDelN twins): an ECT(0) packet arriving while the queue already
# holds >= K packets — or >= K bytes — is marked CE instead of waiting
# for the CoDel control law to drop it.  Both legs are checked against
# the queue state BEFORE this packet enqueues, packets first (the
# attributed MARK_* cause records which leg fired).  ~20 full-MTU
# packets ~= 30 KB, so the two legs agree for bulk traffic and the
# bytes leg catches many-small-segment fan-in.
DCTCP_K_PKTS = 20
DCTCP_K_BYTES = 30_000


def _control_time(first_above_time: int, count: int) -> int:
    """next drop time = t + INTERVAL / sqrt(count), in integer ns."""
    # isqrt on count scaled by 2**32 keeps precision without floats.
    return first_above_time + (INTERVAL_NS << 16) // isqrt(count << 32)


class CoDelQueue:
    __slots__ = ("_q", "_bytes", "_dropping", "_count", "_last_count",
                 "_first_above_time", "_drop_next", "dropped_count",
                 "enqueued_count", "enqueued_bytes", "dropped_bytes",
                 "peak_depth", "marked_count")

    def __init__(self):
        self._q: deque = deque()  # (packet, enqueue_time_ns)
        self._bytes = 0
        self._dropping = False
        self._count = 0
        self._last_count = 0
        self._first_above_time = 0
        self._drop_next = 0
        self.dropped_count = 0
        # Fabric-observatory counters (netplane.cpp CoDelN twins; the
        # conservation invariant is enqueued == forwarded + dropped +
        # still-queued, in both packets and bytes).  `enqueued` counts
        # push ATTEMPTS — hard-limit refusals included — so the
        # invariant holds with the refusal on the dropped side.
        self.enqueued_count = 0
        self.enqueued_bytes = 0
        self.dropped_bytes = 0
        self.peak_depth = 0
        # ECN marks: CE rewrites by the DCTCP-K instantaneous
        # threshold law in push() — a marked packet still FORWARDS, so
        # it sits on the delivered side of the conservation invariant
        # (the fabric channel's qmarks series samples this counter).
        self.marked_count = 0

    def __len__(self):
        return len(self._q)

    def peek_entry(self):
        """Head (packet, enqueue_time_ns) pair or None — the fabric
        sampler's head-of-queue sojourn reading."""
        return self._q[0] if self._q else None

    def _drop(self, packet, on_drop) -> None:
        packet.record(pkt.ST_ROUTER_DROPPED)
        self.dropped_count += 1
        self.dropped_bytes += packet.total_size()
        if on_drop is not None:
            on_drop(packet)

    def push(self, packet, now: int, on_drop=None, on_mark=None,
             k_pkts: int = DCTCP_K_PKTS,
             k_bytes: int = DCTCP_K_BYTES) -> bool:
        """Returns False (and drops) only at the hard limit.  An
        ECN-capable (ECT) packet that clears the hard limit but meets
        the DCTCP-K instantaneous threshold is marked CE and enqueued
        normally; `on_mark(cause)` attributes the mark to the MARK_*
        leg that fired (trace/events.py) — cause-only, so the router
        can pass the host's bound counter method directly.  K is a
        parameter (experimental.dctcp_k_pkts/_bytes — the sweep
        subsystem's congestion axis); the module constants stay the
        twinned defaults."""
        self.enqueued_count += 1
        self.enqueued_bytes += packet.total_size()
        if len(self._q) >= HARD_LIMIT:
            self._drop(packet, on_drop)
            return False
        if packet.ecn == pkt.ECN_ECT0:
            cause = -1
            if len(self._q) >= k_pkts:
                cause = MARK_THRESH_PKTS
            elif self._bytes >= k_bytes:
                cause = MARK_THRESH_BYTES
            if cause >= 0:
                packet.ecn = pkt.ECN_CE
                self.marked_count += 1
                if on_mark is not None:
                    on_mark(cause)
        self._q.append((packet, now))
        self._bytes += packet.total_size()
        if len(self._q) > self.peak_depth:
            self.peak_depth = len(self._q)
        packet.record(pkt.ST_ROUTER_ENQUEUED)
        return True

    def _dequeue_raw(self, now: int):
        """Pop one packet; returns (packet, ok_to_stay_in_drop_state)."""
        if not self._q:
            self._first_above_time = 0
            return None, False
        packet, enq_time = self._q.popleft()
        self._bytes -= packet.total_size()
        sojourn = now - enq_time
        if sojourn < TARGET_NS or self._bytes <= pkt.MTU:
            self._first_above_time = 0
            return packet, False
        if self._first_above_time == 0:
            self._first_above_time = now + INTERVAL_NS
            return packet, False
        return packet, now >= self._first_above_time

    def pop(self, now: int, on_drop=None):
        """CoDel dequeue: may drop packets to signal congestion."""
        packet, ok_to_drop = self._dequeue_raw(now)
        if packet is None:
            self._dropping = False
            return None
        if self._dropping:
            if not ok_to_drop:
                self._dropping = False
            else:
                while now >= self._drop_next and self._dropping:
                    self._drop(packet, on_drop)
                    self._count += 1
                    packet, ok_to_drop = self._dequeue_raw(now)
                    if packet is None:
                        self._dropping = False
                        return None
                    if not ok_to_drop:
                        self._dropping = False
                    else:
                        self._drop_next = _control_time(self._drop_next,
                                                        self._count)
        elif ok_to_drop and (now - self._drop_next < INTERVAL_NS or
                             now - self._first_above_time >= INTERVAL_NS):
            self._drop(packet, on_drop)
            packet, _ = self._dequeue_raw(now)
            if packet is None:
                self._dropping = False
                return None
            self._dropping = True
            # Reuse drop frequency from the last dropping interval if we
            # re-entered quickly (RFC 8289 sec. 4.3).
            if now - self._drop_next < INTERVAL_NS:
                self._count = self._count - self._last_count if self._count > 2 else 1
            else:
                self._count = 1
            self._last_count = self._count
            self._drop_next = _control_time(now, self._count)
        return packet

    def peek(self):
        return self._q[0][0] if self._q else None
