"""Token-bucket rate limiting (ref: src/main/network/relay/token_bucket.rs).

Discrete, integer-ns refills: the bucket refills `refill_size` bytes every
`refill_interval_ns`, capped at `capacity`. Integer arithmetic everywhere —
the conforming-time computation must be identical on every scheduler for
byte-identical traces.
"""

from __future__ import annotations

REFILL_INTERVAL_NS = 1_000_000  # 1 ms, like the reference's relay config


class TokenBucket:
    __slots__ = ("capacity", "refill_size", "refill_interval_ns",
                 "_balance", "_next_refill_time")

    def __init__(self, capacity: int, refill_size: int,
                 refill_interval_ns: int = REFILL_INTERVAL_NS):
        assert capacity > 0 and refill_size > 0
        self.capacity = capacity
        self.refill_size = refill_size
        self.refill_interval_ns = refill_interval_ns
        self._balance = capacity
        self._next_refill_time = 0  # lazily anchored at first use

    @classmethod
    def for_bandwidth(cls, bits_per_sec: int, mtu: int) -> "TokenBucket":
        """Bucket for a link rate: 1ms worth of bytes per refill, with at
        least one MTU of burst so any single packet can always conform
        (relay/mod.rs:278-318)."""
        bytes_per_refill = (bits_per_sec * REFILL_INTERVAL_NS) // (8 * 10**9)
        refill = max(bytes_per_refill, 1)
        return cls(capacity=max(refill, mtu), refill_size=refill)

    def _advance(self, now: int) -> None:
        if self._next_refill_time == 0:
            self._next_refill_time = now + self.refill_interval_ns
            return
        if now >= self._next_refill_time:
            intervals = 1 + (now - self._next_refill_time) // self.refill_interval_ns
            self._balance = min(self.capacity,
                                self._balance + intervals * self.refill_size)
            self._next_refill_time += intervals * self.refill_interval_ns

    def try_remove(self, size: int, now: int):
        """Returns (True, 0) on success or (False, next_refill_time)."""
        self._advance(now)
        if size <= self._balance:
            self._balance -= size
            return True, 0
        return False, self._next_refill_time

    def balance_at(self, now: int) -> int:
        self._advance(now)
        return self._balance

    def peek_balance(self, now: int) -> int:
        """Read-only balance at `now`: the value _advance(now) WOULD
        leave, without mutating.  The fabric observatory samples
        through this — sampling a virgin bucket must not anchor its
        refill clock (the sim must be byte-identical with the channel
        on or off).  Twins: netplane.cpp TokenBucketN::peek_balance
        and the device kernels' bucket_peek."""
        if self._next_refill_time == 0 or now < self._next_refill_time:
            return self._balance
        intervals = 1 + (now - self._next_refill_time) \
            // self.refill_interval_ns
        return min(self.capacity,
                   self._balance + intervals * self.refill_size)
