"""DNS: host registration and name/IP resolution.

Ref: src/main/network/dns.rs:81-190. A flat registry (no hierarchical DNS,
like the reference): every host registers (host_id, ip, name) at build
time; managed code resolves via an /etc/hosts-style file (written into the
data dir) and via direct map lookups from the simulator side.
"""

from __future__ import annotations

from shadow_tpu.net.graph import format_ip


class Dns:
    def __init__(self):
        self._by_name: dict[str, int] = {}   # name -> ip
        self._by_ip: dict[int, tuple[int, str]] = {}  # ip -> (host_id, name)

    def register(self, host_id: int, ip: int, name: str) -> None:
        if name in self._by_name:
            raise ValueError(f"duplicate hostname {name!r}")
        if ip in self._by_ip:
            raise ValueError(f"duplicate IP {format_ip(ip)}")
        self._by_name[name] = ip
        self._by_ip[ip] = (host_id, name)

    def ip_for_name(self, name: str) -> int | None:
        return self._by_name.get(name)

    def host_id_for_ip(self, ip: int) -> int | None:
        entry = self._by_ip.get(ip)
        return entry[0] if entry else None

    def name_for_ip(self, ip: int) -> str | None:
        entry = self._by_ip.get(ip)
        return entry[1] if entry else None

    def hosts_file_text(self) -> str:
        """The /etc/hosts contents exposed to managed code
        (dns.rs:120-150; path export worker.rs:632)."""
        lines = ["127.0.0.1 localhost"]
        for name, ip in sorted(self._by_name.items(), key=lambda kv: kv[1]):
            lines.append(f"{format_ip(ip)} {name}")
        return "\n".join(lines) + "\n"
