"""Minimal DNS wire protocol: answer A-record queries from the sim DNS.

The reference exposes host names to managed code via an /etc/hosts-style
file (src/main/network/dns.rs:81-190 + the hosts-file export).  Our
hybrid fd-space keeps file I/O native, so instead we answer the *DNS
protocol itself*: any UDP datagram a managed process sends to port 53 is
intercepted in the syscall layer and answered from the simulation's name
table — libc's getaddrinfo works unmodified, whatever resolver
/etc/resolv.conf names.
"""

from __future__ import annotations

import struct

QTYPE_A = 1
QTYPE_AAAA = 28
QCLASS_IN = 1

FLAG_RESPONSE = 0x8000
FLAG_RD = 0x0100
FLAG_RA = 0x0080
RCODE_NXDOMAIN = 3


def parse_qname(data: bytes, off: int):
    """-> (name, offset-after) or (None, off) on malformed input."""
    labels = []
    while True:
        if off >= len(data):
            return None, off
        n = data[off]
        if n == 0:
            off += 1
            break
        if n & 0xC0:  # compression pointers: not expected in queries
            return None, off
        off += 1
        if off + n > len(data):
            return None, off
        labels.append(data[off:off + n])
        off += n
    try:
        return b".".join(labels).decode("ascii").lower(), off
    except UnicodeDecodeError:
        return None, off


def answer_query(query: bytes, resolve) -> bytes | None:
    """Build a response for one A/AAAA query.

    `resolve(name) -> ip int | None`.  Returns response bytes, or None
    when the datagram isn't a well-formed single-question query (the
    caller then lets it travel the simulated network like any packet).
    """
    if len(query) < 12:
        return None
    qid, flags, qdcount, _an, _ns, _ar = struct.unpack_from(">6H", query, 0)
    if flags & FLAG_RESPONSE or qdcount != 1:
        return None
    name, off = parse_qname(query, 12)
    if name is None or off + 4 > len(query):
        return None
    qtype, qclass = struct.unpack_from(">2H", query, off)
    off += 4
    if qclass != QCLASS_IN:
        return None
    question = query[12:off]

    ip = resolve(name)
    rflags = FLAG_RESPONSE | FLAG_RA | (flags & FLAG_RD)
    if ip is None:
        header = struct.pack(">6H", qid, rflags | RCODE_NXDOMAIN, 1, 0, 0, 0)
        return header + question
    if qtype == QTYPE_A:
        answer = (b"\xc0\x0c" +                      # pointer to qname
                  struct.pack(">2HIH", QTYPE_A, QCLASS_IN, 60, 4) +
                  int(ip).to_bytes(4, "big"))
        header = struct.pack(">6H", qid, rflags, 1, 1, 0, 0)
        return header + question + answer
    # AAAA (or other types): NOERROR with zero answers -> libc falls
    # back to the A result.
    header = struct.pack(">6H", qid, rflags, 1, 0, 0, 0)
    return header + question
