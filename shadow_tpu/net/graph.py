"""Network graph: GML topology, all-pairs routing, IP assignment.

Re-designs the reference's graph layer (src/main/network/graph/mod.rs and
the gml-parser lib) around a key TPU-first decision: routing is stored as
*dense node-by-node matrices* — int64 latency ns, float64 loss probability
— because the batched packet-propagation kernel gathers `L[src_node,
dst_node]` for a whole round's packets in one vectorized lookup
(ops/propagate.py). Graph nodes number in the thousands even for 100k-host
simulations (hosts attach to nodes), so dense V x V matrices are cheap.

Shortest paths: latency-weighted Dijkstra over all sources
(scipy.sparse.csgraph — replaces the reference's rayon-parallel petgraph
run, graph/mod.rs:183), with packet-loss accumulated *along the chosen
shortest path* via predecessor walking, matching the reference's
PathProperties combination (graph/mod.rs:298-352: latencies add; loss
combines as 1 - prod(1 - loss_i)).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

from shadow_tpu.utils import units


# ---------------------------------------------------------------------------
# GML parsing (format per docs/network_graph_spec.md in the reference)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r'"(?:[^"\\]|\\.)*"|\[|\]|[^\s\[\]]+')


def _tokenize_gml(text: str):
    for line in text.splitlines():
        # '#' comments run to end of line (outside quoted strings; GML
        # labels in network graphs don't contain '#').
        line = line.split("#", 1)[0]
        yield from _TOKEN_RE.findall(line)


def _parse_gml_value(tok: str):
    if tok.startswith('"'):
        return tok[1:-1]
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok


def parse_gml(text: str) -> dict:
    """Parse GML into nested dicts; lists of dicts for repeated keys.

    Returns {"graph": {..., "node": [...], "edge": [...]}}.
    """
    tokens = list(_tokenize_gml(text))
    pos = 0

    def parse_object():
        nonlocal pos
        obj: dict = {}
        while pos < len(tokens):
            key = tokens[pos]
            if key == "]":
                pos += 1
                return obj
            pos += 1
            if pos >= len(tokens):
                raise ValueError(f"GML: dangling key {key!r}")
            if tokens[pos] == "[":
                pos += 1
                value = parse_object()
            else:
                value = _parse_gml_value(tokens[pos])
                pos += 1
            if key in ("node", "edge"):
                obj.setdefault(key, []).append(value)
            else:
                obj[key] = value
        return obj

    root = parse_object()
    if "graph" not in root:
        raise ValueError("GML: no 'graph' object")
    return root


# ---------------------------------------------------------------------------
# Graph model
# ---------------------------------------------------------------------------

@dataclass
class GraphNode:
    gml_id: int
    index: int  # dense 0..V-1 index used by all matrices
    label: str = ""
    bandwidth_down_bits: int | None = None  # node-level host defaults
    bandwidth_up_bits: int | None = None


@dataclass
class GraphEdge:
    source: int  # dense index
    target: int
    latency_ns: int
    jitter_ns: int
    packet_loss: float


# A built-in one-node topology for quick configs (reference: the
# `1_gbit_switch` built-in graph, configuration.rs GraphSource).
BUILTIN_GRAPHS = {
    "1_gbit_switch": """graph [
  directed 0
  node [
    id 0
    label "switch"
    host_bandwidth_down "1 Gbit"
    host_bandwidth_up "1 Gbit"
  ]
  edge [
    source 0
    target 0
    latency "1 ms"
    packet_loss 0.0
  ]
]""",
}


class NetworkGraph:
    """Parsed topology + dense routing matrices.

    Attributes (after `compute_routing`):
      latency_ns:   (V, V) int64 — end-to-end latency, TIME_NEVER if no path
      packet_loss:  (V, V) float64 — end-to-end loss probability
    """

    def __init__(self, nodes: list[GraphNode], edges: list[GraphEdge],
                 directed: bool):
        self.nodes = nodes
        self.edges = edges
        self.directed = directed
        self.by_gml_id = {n.gml_id: n for n in nodes}
        self.latency_ns: np.ndarray | None = None
        self.packet_loss: np.ndarray | None = None
        self.gml_text: str = ""  # original source, for processed-config

    @classmethod
    def from_gml(cls, text: str) -> "NetworkGraph":
        graph = cls._from_gml_parsed(text)
        graph.gml_text = text
        return graph

    @classmethod
    def _from_gml_parsed(cls, text: str) -> "NetworkGraph":
        g = parse_gml(text)["graph"]
        directed = bool(g.get("directed", 0))
        nodes = []
        for i, n in enumerate(g.get("node", [])):
            if "id" not in n:
                raise ValueError("GML node missing 'id'")
            bw_down = n.get("host_bandwidth_down")
            bw_up = n.get("host_bandwidth_up")
            nodes.append(GraphNode(
                gml_id=n["id"], index=i, label=str(n.get("label", "")),
                bandwidth_down_bits=(units.parse_bandwidth_bits(bw_down)
                                     if bw_down is not None else None),
                bandwidth_up_bits=(units.parse_bandwidth_bits(bw_up)
                                   if bw_up is not None else None)))
        by_gml = {n.gml_id: n.index for n in nodes}
        edges = []
        for e in g.get("edge", []):
            if "latency" not in e:
                raise ValueError("GML edge missing 'latency'")
            latency = units.parse_time_ns(e["latency"])
            if latency <= 0:
                raise ValueError("edge latency must be positive (runahead "
                                 "depends on a nonzero minimum latency)")
            edges.append(GraphEdge(
                source=by_gml[e["source"]], target=by_gml[e["target"]],
                latency_ns=latency,
                jitter_ns=units.parse_time_ns(e.get("jitter", 0)),
                packet_loss=float(e.get("packet_loss", 0.0))))
        return cls(nodes, edges, directed)

    @classmethod
    def named(cls, name: str) -> "NetworkGraph":
        return cls.from_gml(BUILTIN_GRAPHS[name])

    @property
    def num_nodes(self) -> int:
        return len(self.nodes)

    def compute_routing(self, use_shortest_path: bool = True) -> None:
        from shadow_tpu.core.simtime import TIME_NEVER

        V = self.num_nodes
        lat = np.full((V, V), np.inf)
        loss_neglog = np.zeros((V, V))
        edge_loss = np.zeros((V, V))
        for e in self.edges:
            pairs = [(e.source, e.target)]
            if not self.directed and e.source != e.target:
                pairs.append((e.target, e.source))
            for s, t in pairs:
                if e.latency_ns < lat[s, t]:
                    lat[s, t] = e.latency_ns
                    edge_loss[s, t] = e.packet_loss

        if use_shortest_path:
            from scipy.sparse import csr_matrix
            from scipy.sparse.csgraph import dijkstra

            w = np.where(np.isinf(lat), 0.0, lat)
            graph = csr_matrix(w)
            dist, pred = dijkstra(graph, directed=True,
                                  return_predecessors=True)
            # Self-paths: a node's self-loop edge if present, else 0-latency
            # local delivery (dijkstra reports dist[i,i]=0 regardless).
            # Accumulate loss along each chosen path by walking predecessors
            # in increasing-distance order (each step's predecessor is
            # already finalized).
            loss = np.zeros((V, V))
            for src in range(V):
                order = np.argsort(dist[src], kind="stable")
                keep = np.ones(V)  # P(not dropped) along path
                for dst in order:
                    p = pred[src, dst]
                    if dst == src or p < 0:
                        continue
                    keep[dst] = keep[p] * (1.0 - edge_loss[p, dst])
                loss[src] = 1.0 - keep
            final_lat = dist
        else:
            # Direct-path mode (graph/mod.rs:230): only explicit edges.
            final_lat = lat
            loss = edge_loss.copy()

        # Self-paths (applied uniformly in both routing modes): prefer an
        # explicit self-loop edge; otherwise use the node's minimum
        # outgoing edge latency as the local-delivery cost. That keeps
        # min_latency_ns() — and with it the runahead window — equal to a
        # *real* edge latency instead of an arbitrary tiny constant, and
        # a truly isolated node's diagonal stays unreachable.
        for i in range(V):
            if np.isfinite(lat[i, i]) and lat[i, i] > 0:
                final_lat[i, i] = lat[i, i]
                loss[i, i] = edge_loss[i, i]
            else:
                out_edges = np.concatenate([lat[i, :i], lat[i, i + 1:]])
                finite = out_edges[np.isfinite(out_edges)]
                final_lat[i, i] = finite.min() if finite.size else np.inf
                loss[i, i] = 0.0

        out = np.where(np.isfinite(final_lat), final_lat, TIME_NEVER)
        self.latency_ns = out.astype(np.int64)
        self.packet_loss = loss
        # Pairwise reachability check happens lazily: send_packet errors on
        # TIME_NEVER entries.

    def min_latency_ns(self) -> int:
        """Smallest possible inter-arrival latency — the runahead floor
        (reference: Runahead min possible latency, runahead.rs:44-116)."""
        assert self.latency_ns is not None
        finite = self.latency_ns[self.latency_ns > 0]
        from shadow_tpu.core.simtime import TIME_NEVER
        finite = finite[finite < TIME_NEVER]
        if finite.size == 0:
            raise ValueError("graph has no usable paths")
        return int(finite.min())


# ---------------------------------------------------------------------------
# IP assignment (reference: src/main/network/graph/mod.rs:354 IpAssignment)
# ---------------------------------------------------------------------------

class IpAssignment:
    """Maps host IPs <-> graph-node indices, auto-assigning from 11.0.0.0/8
    (a public-but-unrouted block, same choice as the reference)."""

    _AUTO_BASE = (11 << 24) + 1

    def __init__(self):
        self._ip_to_node: dict[int, int] = {}
        self._next_auto = self._AUTO_BASE

    def assign(self, node_index: int, ip: int | None = None) -> int:
        if ip is None:
            ip = self._next_auto
            while ip in self._ip_to_node or (ip & 0xFF) in (0, 255):
                ip += 1
            self._next_auto = ip + 1
        elif ip in self._ip_to_node:
            raise ValueError(f"duplicate IP {format_ip(ip)}")
        self._ip_to_node[ip] = node_index
        return ip

    def node_for_ip(self, ip: int) -> int | None:
        return self._ip_to_node.get(ip)


def parse_ip(text: str) -> int:
    parts = [int(p) for p in text.split(".")]
    if len(parts) != 4 or any(not 0 <= p <= 255 for p in parts):
        raise ValueError(f"bad IPv4 address: {text!r}")
    return (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]


def format_ip(ip: int) -> str:
    return f"{ip >> 24 & 255}.{ip >> 16 & 255}.{ip >> 8 & 255}.{ip & 255}"


LOCALHOST_IP = parse_ip("127.0.0.1")
