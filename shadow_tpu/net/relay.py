"""Bandwidth relays (ref: src/main/network/relay/mod.rs:51-318).

A relay moves packets from a source queue to their destination device at a
limited rate (token bucket). Three instances per host: inet-out (upload),
inet-in (download), loopback (unlimited). When the bucket runs dry the
relay schedules its own wakeup task at the next refill instant and
resumes — the reference's self-rescheduling forwarding loop
(relay/mod.rs:201-273).
"""

from __future__ import annotations

from typing import Optional

from shadow_tpu.core.event import TaskRef
from shadow_tpu.net import packet as pkt
from shadow_tpu.net.token_bucket import TokenBucket

# Relay state machine (relay/mod.rs RelayState)
_IDLE = 0
_PENDING = 1


class Relay:
    __slots__ = ("name", "_bucket", "_state", "_pending_packet",
                 "_pop_fn", "stalls", "forwarded_pkts",
                 "forwarded_bytes")

    def __init__(self, name: str, pop_fn, bucket: Optional[TokenBucket]):
        """`pop_fn(host, now)` pops the next packet from the source device;
        bucket=None means unlimited (loopback)."""
        self.name = name
        self._bucket = bucket
        self._state = _IDLE
        self._pending_packet = None  # popped but not yet conforming
        self._pop_fn = pop_fn
        # Fabric-observatory counters (netplane.cpp RelayN twins):
        # packets parked waiting for a bucket refill (the "refill
        # stall" series FB_REC samples), and packets/bytes actually
        # forwarded — the inet-in relay's forwarded counters are the
        # CoDel queue's "delivered" side of the byte-conservation
        # invariant.
        self.stalls = 0
        self.forwarded_pkts = 0
        self.forwarded_bytes = 0

    def ckpt_state(self) -> tuple:
        """Mutable state for a checkpoint (shadow_tpu/ckpt/): the
        relay object itself is NOT pickled — its pop-closure binds the
        owning host — so Host.__setstate__ rebuilds the relay and
        re-applies this tuple."""
        b = self._bucket
        bucket = None if b is None else (b._balance, b._next_refill_time)
        return (self._state, self._pending_packet, bucket, self.stalls,
                self.forwarded_pkts, self.forwarded_bytes)

    def ckpt_restore(self, state: tuple) -> None:
        (self._state, self._pending_packet, bucket, self.stalls,
         self.forwarded_pkts, self.forwarded_bytes) = state
        if bucket is not None and self._bucket is not None:
            self._bucket._balance, self._bucket._next_refill_time = bucket

    def notify(self, host) -> None:
        """Source device has packets; start forwarding unless a wakeup is
        already scheduled (in which case that wakeup will drain us)."""
        if self._state == _PENDING:
            return
        self._forward_until_blocked(host)

    def _wakeup(self, host) -> None:
        # Bound-method TaskRef target: executes as self._wakeup(host).
        self._state = _IDLE
        self._forward_until_blocked(host)

    def _forward_until_blocked(self, host) -> None:
        now = host.now()
        while True:
            packet = self._pending_packet
            self._pending_packet = None
            if packet is None:
                packet = self._pop_fn(host, now)
            if packet is None:
                return
            if self._bucket is not None:
                ok, next_refill = self._bucket.try_remove(
                    packet.total_size(), now)
                if not ok:
                    # Park the packet and self-reschedule at refill time.
                    self.stalls += 1
                    packet.record(pkt.ST_RELAY_CACHED)
                    self._pending_packet = packet
                    self._state = _PENDING
                    assert next_refill > now
                    host.schedule_task_at(
                        next_refill,
                        TaskRef(f"relay-{self.name}", self._wakeup))
                    return
            packet.record(pkt.ST_RELAY_FORWARDED)
            self.forwarded_pkts += 1
            self.forwarded_bytes += packet.total_size()
            dst = host.get_packet_device(packet.dst_ip)
            dst.push(host, packet)
