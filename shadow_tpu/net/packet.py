"""Packets.

Slimmed, SoA-friendly analog of the reference's refcounted packet
(src/main/network/packet.rs:96-460). A packet is a plain slotted object on
the CPU path; the TPU path never sees Python packets — per-round batches
are decomposed into parallel int arrays (src_host, seq, src/dst node,
size) in ops/propagate.py, and only metadata rides to the device (payload
bytes stay host-side; the device computes *scheduling*, not contents).

Identity: (src_host_id, seq) with seq from a per-host monotonic counter —
the RNG key for loss decisions and the determinism tiebreak, assigned at
send time exactly once.

Status breadcrumbs (packet.rs:16-41) are recorded only when tracing is
enabled; they exist for determinism-visible lifecycle debugging.
"""

from __future__ import annotations

from typing import Optional

PROTO_TCP = 6
PROTO_UDP = 17

MTU = 1500  # bytes, fixed like the reference (interface.rs)
IPV4_HEADER_SIZE = 20
UDP_HEADER_SIZE = 8
TCP_HEADER_SIZE = 20

# IP-header ECN codepoints (RFC 3168 sec. 5; netplane.cpp twins).
# Only the two values the stack uses are modeled: a sender stamps
# ECT(0) on ECN-capable data segments, a congested queue rewrites it
# to CE instead of dropping.  Not-ECT is the zero default.
ECN_ECT0 = 2
ECN_CE = 3

# Lifecycle breadcrumbs (subset of packet.rs PacketStatus).
ST_CREATED = "snd_created"
ST_SENT_TO_ROUTER = "snd_to_router"
ST_INET_DROPPED = "inet_dropped"
ST_RELAY_CACHED = "relay_cached"
ST_RELAY_FORWARDED = "relay_forwarded"
ST_ROUTER_ENQUEUED = "rtr_enqueued"
ST_ROUTER_DROPPED = "rtr_dropped"
ST_RCV_INTERFACE = "rcv_interface"
ST_RCV_DELIVERED = "rcv_delivered"


class TcpFlags:
    SYN = 0x02
    ACK = 0x10
    FIN = 0x01
    RST = 0x04
    PSH = 0x08
    URG = 0x20
    # RFC 3168 ECN bits: ECE echoes congestion back to the sender,
    # CWR acknowledges the echo (netplane.cpp F_ECE/F_CWR twins).
    ECE = 0x40
    CWR = 0x80


class TcpHeader:
    __slots__ = ("seq", "ack", "flags", "window", "window_scale", "mss",
                 "sack_blocks", "timestamp", "timestamp_echo")

    def __init__(self, seq=0, ack=0, flags=0, window=0, window_scale=None,
                 mss=None, sack_blocks=(), timestamp=None, timestamp_echo=None):
        self.seq = seq
        self.ack = ack
        self.flags = flags
        self.window = window
        self.window_scale = window_scale  # SYN option
        self.mss = mss                    # SYN option
        self.sack_blocks = sack_blocks
        self.timestamp = timestamp
        self.timestamp_echo = timestamp_echo

    def __repr__(self):
        names = [n for n, bit in (("SYN", TcpFlags.SYN), ("ACK", TcpFlags.ACK),
                                  ("FIN", TcpFlags.FIN), ("RST", TcpFlags.RST),
                                  ("PSH", TcpFlags.PSH)) if self.flags & bit]
        return (f"TcpHeader({'|'.join(names) or '.'} seq={self.seq} "
                f"ack={self.ack} win={self.window})")


_trace_enabled = False


def set_status_tracing(enabled: bool) -> None:
    global _trace_enabled
    _trace_enabled = enabled


class Packet:
    __slots__ = ("src_host_id", "seq", "protocol", "src_ip", "src_port",
                 "dst_ip", "dst_port", "payload", "tcp", "priority",
                 "statuses", "arrival_time", "ecn", "_total_size")

    def __init__(self, src_host_id: int, seq: int, protocol: int,
                 src_ip: int, src_port: int, dst_ip: int, dst_port: int,
                 payload: bytes = b"", tcp: Optional[TcpHeader] = None):
        self.src_host_id = src_host_id
        self.seq = seq
        self.protocol = protocol
        self.src_ip = src_ip
        self.src_port = src_port
        self.dst_ip = dst_ip
        self.dst_port = dst_port
        self.payload = payload
        self.tcp = tcp
        self.priority = 0       # FIFO stamp assigned at interface enqueue
        self.statuses = None
        self.arrival_time = 0   # set by the propagation phase
        # IP ECN codepoint: ECN_ECT0 on ECN-capable data segments
        # (stamped by the sending socket), rewritten to ECN_CE by a
        # congested queue's marking law, 0 (not-ECT) otherwise.
        self.ecn = 0
        # Hot-path cache: headers and payload never change after
        # construction, and total_size() is called several times per
        # packet in the queue/relay path.
        self._total_size = self.header_size() + len(payload)
        if _trace_enabled:
            self.statuses = [ST_CREATED]

    def record(self, status: str) -> None:
        if self.statuses is not None:
            self.statuses.append(status)

    def header_size(self) -> int:
        return IPV4_HEADER_SIZE + (
            TCP_HEADER_SIZE if self.protocol == PROTO_TCP else UDP_HEADER_SIZE)

    def total_size(self) -> int:
        return self._total_size

    def is_empty_control(self) -> bool:
        """Control packets (no payload) are exempt from random loss, like
        the reference's empty-packet exemption (worker.rs:362-365) — pure
        ACK/SYN/FIN loss would make TCP converge needlessly slowly."""
        return len(self.payload) == 0

    def __repr__(self):
        from shadow_tpu.net.graph import format_ip
        p = "tcp" if self.protocol == PROTO_TCP else "udp"
        return (f"Packet[{p} {format_ip(self.src_ip)}:{self.src_port}->"
                f"{format_ip(self.dst_ip)}:{self.dst_port} len={len(self.payload)} "
                f"id=({self.src_host_id},{self.seq})]")
