"""Per-host router (ref: src/main/network/router/mod.rs).

Two roles, like the reference:
- *inbound*: packets arriving from the simulated internet are queued in a
  CoDel AQM until the host's download-bandwidth relay forwards them to the
  interface;
- *outbound*: pushing a packet to the router hands it to the cross-host
  propagation backend (the scheduler's `send_packet`), i.e. the router IS
  the host's porthole to the batched TPU path.
"""

from __future__ import annotations

from shadow_tpu.net import packet as pkt
from shadow_tpu.net.codel import CoDelQueue


class Router:
    __slots__ = ("_inbound",)

    def __init__(self):
        self._inbound = CoDelQueue()

    # --- inbound side (from the network, toward the host) ---

    def route_incoming_packet(self, host, packet) -> None:
        """Called by the scheduler when a cross-host packet arrives at this
        host (Host::execute packet branch, host.rs:783-786)."""
        if self._inbound.push(packet, host.now(),
                              lambda p: host.trace_drop(p, "rtr-limit"),
                              host.count_mark,
                              k_pkts=host.dctcp_k_pkts,
                              k_bytes=host.dctcp_k_bytes):
            host.notify_router_has_packets()

    def pop_inbound(self, host, now: int):
        return self._inbound.pop(now, lambda p: host.trace_drop(p, "codel"))

    def has_inbound(self) -> bool:
        return len(self._inbound) > 0

    @property
    def inbound_dropped(self) -> int:
        return self._inbound.dropped_count

    # --- outbound side (from the host, toward the network) ---

    def route_outgoing_packet(self, host, packet) -> None:
        packet.record(pkt.ST_SENT_TO_ROUTER)
        host.send_packet(packet)

    # PacketDevice interface: pushing *to* the router means "toward the
    # internet" (mod.rs:16-20).
    push = route_outgoing_packet
