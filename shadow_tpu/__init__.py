"""shadow_tpu: a TPU-native discrete-event network simulation framework.

A ground-up re-design of the Shadow simulator (reference: /root/reference,
see SURVEY.md) for TPU hardware: the per-host discrete-event loop runs on
CPU, while cross-host packet propagation (latency lookup, loss, arrival-time
computation for every in-flight packet of every host), transport-state
stepping, and the conservative round barrier's global min-next-event-time
reduction run as batched JAX/XLA kernels over a host-sharded device mesh.

Layering (mirrors reference layer map, SURVEY.md section 1):
  core/      time, events, rounds, scheduling, config    (ref: src/main/core/)
  host/      the simulated Linux kernel per virtual host (ref: src/main/host/)
  net/       packets, graph, router, relay, DNS          (ref: src/main/network/)
  tcp/       sans-I/O TCP state machine                  (ref: src/lib/tcp/)
  ops/       batched JAX/XLA kernels (the TPU data path)
  parallel/  device meshes, sharding, collective barriers
  utils/     pcap, counters, units, status
"""

# Simulation times are 64-bit nanosecond counts; JAX must not silently
# truncate them to 32 bits anywhere on the device path.
import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
