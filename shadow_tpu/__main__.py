"""CLI: `python -m shadow_tpu [options] config.yaml`.

The run_shadow equivalent (ref: src/main/main.c -> src/main/shadow.rs:30
and the clap CLI in src/main/core/configuration.rs:51-120): load YAML,
apply CLI overrides, run, write the data directory, exit nonzero if any
process ended in an unexpected state.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow-tpu",
        description="TPU-native discrete-event network simulator")
    p.add_argument("config", nargs="?", help="YAML simulation config")
    p.add_argument("--seed", type=int, help="override general.seed")
    p.add_argument("--stop-time", help="override general.stop_time")
    p.add_argument("--parallelism", type=int,
                   help="override general.parallelism")
    p.add_argument("--data-directory", help="override data directory")
    p.add_argument("--scheduler",
                   choices=["serial", "thread_per_core", "thread_per_host",
                            "tpu"],
                   help="override experimental.scheduler")
    p.add_argument("--progress", action="store_true",
                   help="print heartbeat progress to stderr")
    p.add_argument("--strace-logging-mode",
                   choices=["off", "standard", "deterministic"],
                   help="per-process syscall logs")
    p.add_argument("--flight-recorder", choices=["off", "wall", "on"],
                   help="deterministic flight recorder "
                        "(docs/OBSERVABILITY.md): 'on' records the "
                        "sim-time event stream + wall phases into the "
                        "data dir, 'wall' phases only")
    p.add_argument("--syscall-observatory", choices=["off", "wall", "on"],
                   help="per-syscall telemetry for managed processes "
                        "(docs/OBSERVABILITY.md): 'on' records the "
                        "deterministic syscalls-sim.bin channel + the "
                        "wall-time IPC profile, 'wall' the profile only")
    p.add_argument("--resume", metavar="SNAPSHOT",
                   help="resume from a checkpoint archive written by a "
                        "`checkpoint:` config block (docs/CHECKPOINT.md); "
                        "the config must match the snapshotted run")
    p.add_argument("--show-build-info", action="store_true")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.show_build_info:
        import shadow_tpu
        print(f"shadow-tpu {shadow_tpu.__version__}")
        return 0
    if args.config is None:
        parser.print_usage(sys.stderr)
        print("shadow-tpu: error: the config argument is required",
              file=sys.stderr)
        return 2

    import yaml

    # Honor JAX_PLATFORMS before any backend initializes: the site TPU
    # plugin force-sets jax_platforms at interpreter startup, so the env
    # var alone cannot keep a CLI run on CPU (utils/platform.py).
    from shadow_tpu.utils.platform import honor_platform_env
    honor_platform_env()

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import resume_simulation, run_simulation
    from shadow_tpu.utils import units

    try:
        config = ConfigOptions.from_file(args.config)
    except (OSError, ValueError, yaml.YAMLError) as e:
        print(f"[shadow-tpu] bad config {args.config!r}: {e}",
              file=sys.stderr)
        return 1
    if args.seed is not None:
        config.general.seed = args.seed
    if args.stop_time is not None:
        config.general.stop_time_ns = units.parse_time_ns(args.stop_time)
    if args.parallelism is not None:
        config.general.parallelism = args.parallelism
    if args.data_directory is not None:
        config.general.data_directory = args.data_directory
    if args.scheduler is not None:
        config.experimental.scheduler = args.scheduler
    if args.progress:
        config.general.progress = True
    if args.strace_logging_mode is not None:
        config.experimental.strace_logging_mode = args.strace_logging_mode
    if args.flight_recorder is not None:
        config.experimental.flight_recorder = args.flight_recorder
    if args.syscall_observatory is not None:
        config.experimental.syscall_observatory = args.syscall_observatory

    if args.resume is not None:
        from shadow_tpu.ckpt.format import CkptError
        try:
            manager, summary = resume_simulation(config, args.resume,
                                                 write_data=True)
        except CkptError as e:
            print(f"[shadow-tpu] resume failed: {e}", file=sys.stderr)
            return 1
    else:
        manager, summary = run_simulation(config, write_data=True)
    if summary.plugin_errors:
        for err in summary.plugin_errors:
            print(f"[shadow-tpu] plugin error: {err}", file=sys.stderr)
        return 1
    print(f"[shadow-tpu] done: simulated {summary.end_time_ns / 1e9:.3f}s "
          f"in {summary.rounds} rounds; {summary.packets_sent} packets, "
          f"{summary.syscalls} syscalls", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
