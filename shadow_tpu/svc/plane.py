"""Syscall service plane: batched managed-process servicing.

The syscall observatory (PR 6) measured where `bench[managed-128]`'s
wall goes: one futex wait/dispatch/resume round trip per syscall, run
from the scheduler's SERIAL per-host walk — while host A's native
process computes between syscalls, every other managed host waits its
turn.  This plane lifts managed-host servicing out of that walk into a
host-affine worker pool, the same shape as Laminar's move of TCP
protocol work off the per-connection hot path into parallel engines
(PAPERS.md, arXiv 2504.19058): batch the control plane, keep wakeups
off the hot path.

Determinism argument (the whole design hangs on it):

- A conservative round's hosts are independent by construction — the
  window is narrower than the minimum cross-host latency, so nothing
  one host does inside the window can reach another host inside the
  same window.  Executing them concurrently is exactly what the
  thread_per_core scheduler already proves byte-safe.
- Per-host event order is untouched: each host's whole
  ``execute(until)`` runs as one unit on one worker group (hosts are
  assigned by ``host.id % workers`` — host-affine, stable for the
  run), so the host-serial syscall dispatch order — and with it the
  byte-identical ``syscalls-sim.bin`` channel — is preserved.
- Cross-host effects go through the propagator's ``send`` and the
  destination inbox, both thread-safe (the manager arms the scalar
  propagator's threaded mode whenever this plane is active).

The wall win: workers blocked in the IPC futex recv release the GIL
(the wait is a raw libc syscall), so N managed hosts' round trips
overlap instead of serializing — and the v8 IPC protocol rev this PR
ships (shim_ipc.h) drops the consumer-side FUTEX_WAKE from both
directions and lets the shim spin briefly for fast answers while the
plane advertises itself via the svc_flags header word.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor


class SyscallServicePlane:
    """Host-affine worker pool draining managed hosts' due servicing
    work each conservative round.

    ``dispatch(hosts, until)`` partitions the round's due managed
    hosts into ``workers`` affinity groups (``host.id % workers``,
    each group in ascending host id) and returns a join callable; the
    manager runs the rest of the round's hosts while the groups drain,
    then joins before the propagation barrier."""

    def __init__(self, workers: int):
        assert workers >= 1
        self.workers = workers
        self._pool = ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix="svc-worker")
        # Wall-side telemetry for metrics.wall.svc.
        self.rounds = 0          # rounds with >= 1 managed host due
        self.hosts_serviced = 0  # host-rounds drained by the pool

    @staticmethod
    def _run_group(group, until: int) -> None:
        for h in group:
            h.execute(until)

    def dispatch(self, hosts, until: int):
        """Start draining `hosts` (due managed hosts, ascending id);
        returns a 0-arg join callable that re-raises the first worker
        exception.  An empty host list returns a no-op join."""
        if not hosts:
            return lambda: None
        self.rounds += 1
        self.hosts_serviced += len(hosts)
        n = self.workers
        groups = [[] for _ in range(n)]
        for h in hosts:  # ascending id in, ascending id per group out
            groups[h.id % n].append(h)
        futures = [self._pool.submit(self._run_group, g, until)
                   for g in groups if g]

        def join():
            for f in futures:
                f.result()  # re-raise worker exceptions in round order
        return join

    def wall_summary(self) -> dict:
        """The metrics.wall.svc block."""
        return {"workers": self.workers, "rounds": self.rounds,
                "hosts_serviced": self.hosts_serviced}

    def shutdown(self) -> None:
        self._pool.shutdown()
