"""Syscall service plane (docs/OBSERVABILITY.md "Syscall service
plane"): batched, host-affine servicing of managed-process syscalls —
ROADMAP item 2's engine.  See svc/plane.py."""

from shadow_tpu.svc.containment import ContainmentPlane
from shadow_tpu.svc.plane import SyscallServicePlane

__all__ = ["SyscallServicePlane", "ContainmentPlane"]
