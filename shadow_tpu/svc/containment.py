"""Failure containment plane (docs/ROBUSTNESS.md).

Shadow's value is that real, unmodified binaries run inside a
deterministic simulation — but wall-side failures (a segfaulting
binary, a userspace spin that never syscalls, a posix_spawn that loses
a race against the kernel's fork budget) are events the simulation
does not own.  This plane converts each of them into a deterministic,
attributed SIM-side outcome instead of a crashed or poisoned run:

 - **Triggers** (host/managed.py seams): unexpected binary death
   (final state mismatch at process exit), the wall-time hang
   watchdog (`experimental.managed_watchdog`), and spawn failure
   after the bounded EAGAIN/ENOMEM retries.
 - **Policy** (per-process `on_failure: abort|quarantine|restart`):
   `abort` keeps the historical plugin-error semantics; `restart`
   re-spawns the binary at the failure instant up to
   `restart_budget` times; `quarantine` — and restart exhaustion —
   kills the whole host (the PR 8 `host_kill` machinery, host-down
   drop attribution) at the NEXT conservative-round boundary.
 - **Ledger**: every containment action is recorded.  The `ops`
   section (at_ns/action/host) is exactly a `faults:` schedule;
   re-running with it supplied reproduces the run byte-identically
   when the underlying failure is deterministic (the honest
   determinism contract for nondeterministic wall events —
   docs/ROBUSTNESS.md spells out the limits).

Determinism argument: a failure is DETECTED at a simulated instant
(the host-serial event being serviced when the manager notices — a
pure function of the binary's behavior, not of wall time), and every
containment EFFECT applies either at that instant (restart respawn)
or at the next round boundary (quarantine), both pure functions of
sim state.  Wall time decides only *whether* the watchdog fires —
never *where* its effects land.
"""

from __future__ import annotations

import threading
import time as _walltime

# Bounded posix_spawn retry on transient kernel pressure
# (EAGAIN/ENOMEM): wall-side only, engaged before the containment
# policy.  4 attempts spanning ~150ms of backoff rides out a
# same-round spawn storm without stalling a genuinely broken host.
SPAWN_RETRIES = 3
SPAWN_BACKOFF_S = 0.01  # doubles per attempt: 10/20/40 ms

# Causes (ledger `events` entries; deterministic strings).
CAUSE_DEATH = "binary-death"
CAUSE_HANG = "hang-watchdog"
CAUSE_SPAWN = "spawn-failure"
CAUSE_BUDGET = "restart-exhausted"


class _SpawnGate:
    """Wall-time spawn stagger (experimental.managed_spawn_stagger):
    successive managed posix_spawns across the whole run keep at least
    `stagger_ns` of wall distance, so a 10k-binary fleet spawning in
    one round becomes a bounded-rate stream instead of a fork storm.
    Wall-only: simulation bytes are identical at any stagger."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0.0

    def wait(self, stagger_ns: int) -> None:
        if stagger_ns <= 0:
            return
        with self._lock:
            now = _walltime.monotonic()  # shadow-lint: allow[wall-clock] spawn-stagger pacing (wall-only knob)
            wait_s = self._next - now
            self._next = max(self._next, now) + stagger_ns / 1e9
        if wait_s > 0:
            _walltime.sleep(wait_s)  # shadow-lint: allow[wall-clock] spawn-stagger pacing (wall-only knob)


SPAWN_GATE = _SpawnGate()


class ContainmentPlane:
    """Owned by the Manager when managed (real-binary) processes are
    configured; hosts reach it via ``host.containment``.  Thread-safe:
    triggers fire from svc-plane workers and scheduler threads."""

    def __init__(self, watchdog_ns: int = 0):
        self.watchdog_ns = int(watchdog_ns)
        self._lock = threading.Lock()
        # host id -> first cause; applied (and cleared) by the round
        # loop at the next conservative-round boundary.
        self._pending: dict[int, str] = {}
        # (host_id, spawn_tag) -> restarts consumed.
        self._restarts: dict[tuple, int] = {}
        # Ledger: `ops` are the replayable quarantine applications
        # (appended by the manager's apply path, in application
        # order); `events` are every containment trigger/action with
        # its cause (appended here, canonically sorted at write).
        self.ops: list[dict] = []
        self._events: list[dict] = []
        # The round loop is live: containment triggers outside it
        # (end-of-run forced teardown) must not engage.
        self.active = True

    # -- trigger side (managed.py seams) ------------------------------

    @property
    def has_pending(self) -> bool:
        return bool(self._pending)

    def _note(self, at_ns: int, host, process, cause: str,
              action: str, detail: str) -> None:
        with self._lock:
            self._events.append({
                "at_ns": int(at_ns), "host": host.name,
                "host_id": host.id, "process": process.name,
                "cause": cause, "action": action, "detail": detail,
            })

    def process_failed(self, host, process, cause: str,
                       detail: str = "") -> bool:
        """A managed process failed against its expected final state.
        Returns True when the failure was CONTAINED.  The actual
        suppression contract is `process.contained` (set here, read
        by the manager's final accounting) — the return value is
        informational only."""
        policy = getattr(process, "on_failure", "abort")
        if not self.active or policy == "abort":
            return False
        if getattr(process, "_hang_killed", False):
            cause = CAUSE_HANG
        process.contained = cause
        at = host.now()
        tag = getattr(process, "spawn_tag", None)
        pcfg = getattr(process, "_pcfg", None)
        if policy == "restart" and cause != CAUSE_SPAWN \
                and tag is not None and pcfg is not None:
            key = (host.id, tag)
            with self._lock:
                used = self._restarts.get(key, 0)
                budget_left = used < int(
                    getattr(process, "restart_budget", 0))
                if budget_left:
                    self._restarts[key] = used + 1
            if budget_left:
                from shadow_tpu.core.event import TaskRef
                from shadow_tpu.core.manager import SpawnTask
                self._note(at, host, process, cause, "restart", detail)
                host.schedule_task_at(
                    at, TaskRef("containment-restart",
                                SpawnTask(pcfg, tag)))
                return True
            cause = CAUSE_BUDGET
            process.contained = cause
        self._note(at, host, process, cause, "quarantine", detail)
        with self._lock:
            self._pending.setdefault(host.id, cause)
        return True

    def hang_kill(self, host, thread) -> bool:
        """Watchdog expiry on a managed thread's IPC recv: SIGKILL the
        native process so the recv resolves through the normal death
        path (which re-enters process_failed with the hang cause).
        Returns True when a kill was issued."""
        import os
        import signal
        process = thread.process
        if not self.active or process.exited or \
                process.native_pid is None:
            return False
        process._hang_killed = True
        try:
            os.kill(process.native_pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        return True

    # -- apply side (the manager's round loop) ------------------------

    def take_pending(self) -> list[tuple[int, str]]:
        """Due quarantines in ascending host-id order (deterministic
        application order at the boundary)."""
        with self._lock:
            out = sorted(self._pending.items())
            self._pending.clear()
        return out

    def record_op(self, at_ns: int, host_name: str) -> None:
        """One APPLIED quarantine (containment-triggered or a replayed
        `faults:` op) — the replayable ledger section."""
        with self._lock:
            self.ops.append({"at": f"{int(at_ns)} ns",
                             "action": "quarantine",
                             "host": host_name})

    def ledger(self) -> dict:
        """The fault-ledger artifact: `ops` in application order
        (already deterministic), `events` canonically sorted — worker
        threads may interleave appends across hosts."""
        with self._lock:
            events = sorted(self._events,
                            key=lambda e: (e["at_ns"], e["host_id"],
                                           e["process"], e["cause"]))
            return {"ops": list(self.ops), "events": events}


def preflight_managed(n_processes: int, warn_only: bool,
                      log=None) -> None:
    """Resource preflight for large managed fleets: size the fd table
    and /dev/shm against the configured fleet BEFORE spawning.  Each
    managed process costs the manager ~8 fds (IPC block, /proc/pid/mem,
    transfer socketpair, stdio redirect files, pidfd) and one ~600 KiB
    /dev/shm IPC block.  Failing fast with the exact limit to raise
    beats 9k successful spawns followed by EMFILE mid-run.  Under an
    all-quarantine fleet (warn_only) a breach degrades to containment,
    so warn instead of refusing."""
    import os
    import resource
    import warnings

    problems = []
    fds_needed = 8 * n_processes + 256
    try:
        soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    except (ValueError, OSError):  # pragma: no cover
        soft = None
    if soft is not None and soft < fds_needed:
        problems.append(
            f"fd table: RLIMIT_NOFILE soft limit is {soft} but "
            f"{n_processes} managed processes need ~{fds_needed} "
            f"(raise it: `ulimit -n {fds_needed}` or "
            f"LimitNOFILE in the service unit)")
    # IpcBlock is ~a few hundred KiB of shared memory per process;
    # budget 1 MiB each for headroom.
    shm_needed = n_processes * (1 << 20)
    try:
        st = os.statvfs("/dev/shm")
        shm_free = st.f_bavail * st.f_frsize
    except OSError:  # pragma: no cover
        shm_free = None
    if shm_free is not None and shm_free < shm_needed:
        problems.append(
            f"/dev/shm: {shm_free // (1 << 20)} MiB free but "
            f"{n_processes} managed processes need "
            f"~{shm_needed // (1 << 20)} MiB of IPC blocks (remount: "
            f"`mount -o remount,size={2 * shm_needed // (1 << 20)}M "
            f"/dev/shm`)")
    if not problems:
        return
    msg = ("managed-fleet resource preflight: "
           + "; ".join(problems))
    if warn_only:
        warnings.warn(msg + " — continuing because every managed "
                      "process runs under on_failure: quarantine")
        if log is not None:
            log(msg)
    else:
        raise RuntimeError(
            msg + " (or set on_failure: quarantine on every managed "
            "process to degrade instead of failing)")
