"""Experiment-fleet subsystem: declarative tail-latency campaigns
(docs/SWEEP.md).

- spec.py     — campaign spec -> deterministic run matrix
- runner.py   — identity-safe subprocess execution, optional
                warm-start forking on the checkpoint substrate
- dataset.py  — per-point artifacts -> ONE canonical byte-stable
                dataset + tail-curve tables
- point.py    — the per-point subprocess entry
"""
