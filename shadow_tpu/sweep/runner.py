"""Campaign runner: execute the expanded run matrix in identity-safe
subprocesses, optionally warm-starting fork groups from a shared
post-ramp checkpoint.

Cold path (the default): one `python -m shadow_tpu.sweep.point`
subprocess per point, each with its own data directory and the spec's
per-point wall limit.

Warm path (`warm_start: {at_ms: N}`): points are grouped by their
fork-group key (sweep/spec.expand — everything but the fork-safe
axes).  Each group runs ONE ramp subprocess (the group's first point,
with a checkpoint scheduled at the warm-start instant), the snapshot
is forked per point via ckpt/fork.fork_archive (digest re-stamped for
the point's dctcp_k variant), and each point's subprocess RESUMES its
forked archive.  Warm-started variants share the ramp's bytes by
construction — the dataset records `warm_started` so nobody mistakes
a forked point for a cold run of the same config.

Determinism: subprocess stdout/stderr and wall times go to
`run.json`-adjacent logs, never into the dataset; the dataset reads
only the deterministic channels.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from shadow_tpu.sweep import spec as spec_mod


class PointFailure(RuntimeError):
    """A campaign point exited nonzero / timed out past its retry
    budget AND the campaign's max_failed_points allowance; the
    campaign fails loudly rather than aggregating a hole.  Within the
    allowance, failed points are recorded honestly in the manifest
    (and from there in the `.swds` metadata) instead."""


# Wall backoff between per-point retry attempts (docs/ROBUSTNESS.md
# "Self-healing sweeps"): transient failures — an OOM-killed
# subprocess, a wall-limit near-miss on a loaded box — deserve a
# breather; deterministic failures fail every attempt identically.
RETRY_BACKOFF_S = 2.0


def _point_env() -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _run_sub(task: dict, task_path: str, log_path: str,
             time_limit_s: float) -> None:
    with open(task_path, "w") as f:
        json.dump(task, f)
    with open(log_path, "w") as log:
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "shadow_tpu.sweep.point",
                 task_path],
                stdout=log, stderr=subprocess.STDOUT,
                env=_point_env(), timeout=time_limit_s,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))))
        except subprocess.TimeoutExpired:
            raise PointFailure(
                f"{os.path.basename(task_path)}: exceeded the "
                f"per-point time limit ({time_limit_s}s) — see "
                f"{log_path}") from None
    if proc.returncode != 0:
        tail = open(log_path).read()[-800:]
        raise PointFailure(
            f"{os.path.basename(task_path)}: exit "
            f"{proc.returncode}\n{tail}")


def point_task(spec: dict, point: dict, data_dir: str) -> dict:
    """THE task-dict recipe for one campaign point — run_campaign and
    bench's identity re-run both build through here, so the two can
    never drift into comparing differently-configured runs."""
    return {
        "yaml": spec_mod.point_yaml(spec, point),
        "data_dir": data_dir,
        "experimental": spec_mod.point_experimental(spec, point),
        "link_interval_ms": spec_mod.validate_spec(
            spec)["link_interval_ms"],
    }


# Sim-time headroom the warm-start ramp runs past its checkpoint
# instant: the snapshot lands at the first conservative-round boundary
# >= at_ms, so the ramp needs a little room after it — but nothing
# like the full scenario stop_time (the ramp is overhead; variants do
# the real running).
RAMP_HEADROOM_NS = 100_000_000


def _scenario_stop_ns(spec: dict) -> int:
    """The campaign's sim stop time in ns (spec.base or the netgen
    scenario default) — the warm-start gate needs it to refuse a ramp
    at/after the end."""
    from shadow_tpu.utils import units
    defaults = {"incast": "3s", "rpc_burst": "3s", "leaf_spine": "5s"}
    stop = spec["base"].get("stop_time",
                            defaults[spec["scenario"]])
    return units.parse_time_ns(stop)


def _write_manifest(out_dir: str, spec: dict, manifest: dict) -> None:
    """Persisted INCREMENTALLY after every point so a killed campaign
    resumes from exactly what completed (`tools/sweep run --resume`)."""
    failed = sorted(pid for pid, ent in manifest.items()
                    if ent.get("status") == "failed")
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump({"spec": spec, "points": manifest,
                   "failed_points": failed}, f,
                  sort_keys=True, indent=1)


def _attempt_point(task: dict, pdir: str, time_limit_s: float,
                   retries: int, log) -> tuple[bool, str, int]:
    """Run one point with the retry budget: (ok, error, attempts).
    The completion marker (`complete.json`) is written only after a
    clean exit — `--resume` trusts the marker, never a half-written
    data dir.  A stale marker from an EARLIER run is removed first,
    so a point that fails now cannot be mistaken for complete by a
    later resume."""
    import time as _walltime
    try:
        os.remove(os.path.join(pdir, "complete.json"))
    except OSError:
        pass
    err = ""
    for attempt in range(retries + 1):
        if attempt:
            log(f"sweep: retry {attempt}/{retries} "
                f"{os.path.basename(pdir)}")
            _walltime.sleep(RETRY_BACKOFF_S * attempt)  # shadow-lint: allow[wall-clock] per-point retry backoff (wall-side fleet control)
        try:
            _run_sub(task, os.path.join(pdir, "task.json"),
                     os.path.join(pdir, "log.txt"), time_limit_s)
        except PointFailure as e:
            err = str(e)
            continue
        with open(os.path.join(pdir, "complete.json"), "w") as f:
            json.dump({"attempts": attempt + 1}, f)
        return True, "", attempt + 1
    return False, err, retries + 1


def run_campaign(spec: dict, out_dir: str,
                 log=lambda msg: print(msg, file=sys.stderr),
                 resume: bool = False) -> dict:
    """Execute every point of `spec` under `out_dir` (one
    subdirectory per point, `<point_id>/`).  Returns the manifest
    points mapping {point_id: {dir, warm_started, group, status,
    attempts}} in matrix order.

    Self-healing (docs/ROBUSTNESS.md): each point retries up to
    `spec.retries` times with bounded backoff; a point that still
    fails is RECORDED (status "failed" + the error) rather than
    aborting, until more than `spec.max_failed_points` have failed —
    then PointFailure aborts the campaign.  With `resume=True`,
    points whose completion marker exists are skipped, so a killed or
    partially-failed campaign re-runs only the missing work."""
    spec = spec_mod.validate_spec(spec)
    points = spec_mod.expand(spec)
    os.makedirs(out_dir, exist_ok=True)
    if resume:
        # point_ids encode only seed+axes: a changed `base`/`scenario`
        # would silently reuse data generated under the OLD spec.
        # The manifest stores the spec it ran with — refuse a resume
        # under a different one.
        man_path = os.path.join(out_dir, "manifest.json")
        if os.path.exists(man_path):
            with open(man_path) as f:
                stored = json.load(f).get("spec")
            if stored is not None and stored != spec:
                raise PointFailure(
                    f"--resume refused: {out_dir} was run under a "
                    f"DIFFERENT spec (point ids encode only "
                    f"seed+axes, so completed points would be reused "
                    f"under the wrong base config) — use a fresh "
                    f"--out directory")
    warm = spec["warm_start"]
    manifest: dict = {}
    failed = 0
    groups: dict = {}
    for p in points:
        groups.setdefault(p["group"], []).append(p)

    if warm is not None:
        ramp_ns = warm["at_ms"] * 1_000_000
        stop_ns = _scenario_stop_ns(spec)
        if ramp_ns >= stop_ns:
            raise spec_mod.SpecError(
                f"warm_start.at_ms ({warm['at_ms']} ms) is not "
                f"before the scenario stop_time "
                f"({stop_ns // 1_000_000} ms)")

    def record_failure(p, pdir, err, attempts) -> None:
        nonlocal failed
        failed += 1
        manifest[p["point_id"]] = {
            "dir": pdir, "group": p["group"],
            "warm_started": warm is not None,
            "status": "failed", "error": err[-800:],
            # 0 = the point itself never ran (its ramp failed).
            "attempts": attempts,
        }
        _write_manifest(out_dir, spec, manifest)
        if failed > spec["max_failed_points"]:
            raise PointFailure(
                f"{p['point_id']}: {err}\n(campaign aborted: "
                f"{failed} failed points exceeds max_failed_points="
                f"{spec['max_failed_points']})")
        log(f"sweep: point {p['point_id']} FAILED "
            f"({failed}/{spec['max_failed_points']} budget) — "
            f"recorded, campaign continues")

    for gname, gpoints in groups.items():
        snap = None
        ramp_task = None
        pending = []
        for p in gpoints:
            pdir = os.path.join(out_dir, p["point_id"])
            if resume and os.path.exists(
                    os.path.join(pdir, "complete.json")):
                log(f"sweep: point {p['point_id']} already complete "
                    f"(resume) — skipped")
                manifest[p["point_id"]] = {
                    "dir": pdir, "group": p["group"],
                    "warm_started": warm is not None,
                    "status": "ok", "attempts": 0,
                }
                continue
            pending.append(p)
        if not pending:
            continue
        if warm is not None:
            # ONE ramp per fork group: the group's first point's
            # scenario config with the group-base experimental
            # values, checkpointed at the warm-start boundary and
            # STOPPED just past it (the full stop_time is the
            # variants' job; stop_time is fork-safe, so the truncated
            # ramp archive forks to full-length variants).
            ramp_ns = warm["at_ms"] * 1_000_000
            ramp_dir = os.path.join(out_dir, f"ramp.{gname}")
            os.makedirs(ramp_dir, exist_ok=True)
            ramp_task = point_task(spec, gpoints[0], ramp_dir)
            ramp_task["checkpoint"] = {"at_ns": [ramp_ns],
                                       "directory": ramp_dir}
            ramp_task["stop_time_ns"] = min(
                _scenario_stop_ns(spec), ramp_ns + RAMP_HEADROOM_NS)
            snap = os.path.join(ramp_dir, f"ckpt-{ramp_ns}.stck")
            if resume and os.path.exists(snap) and os.path.exists(
                    os.path.join(ramp_dir, "complete.json")):
                # The ramp is the expensive part warm-start exists to
                # amortize: a completed ramp's snapshot is reused.
                log(f"sweep: ramp [{gname}] already complete "
                    f"(resume) — snapshot reused")
                ok, err = True, ""
            else:
                log(f"sweep: ramp [{gname}] -> checkpoint at "
                    f"{warm['at_ms']} ms")
                ok, err, _n = _attempt_point(
                    ramp_task, ramp_dir, spec["time_limit_s"],
                    spec["retries"], log)
                if ok and not os.path.exists(snap):
                    ok, err = False, (
                        f"ramp [{gname}] wrote no snapshot at "
                        f"{warm['at_ms']} ms (boundary never reached "
                        f"before stop_time?)")
            if not ok:
                # A dead ramp takes its whole fork group with it —
                # every pending member fails against the budget
                # (attempts 0: the points themselves never ran).
                for p in pending:
                    pdir = os.path.join(out_dir, p["point_id"])
                    os.makedirs(pdir, exist_ok=True)
                    record_failure(p, pdir, f"ramp failed: {err}", 0)
                continue

        for p in pending:
            pdir = os.path.join(out_dir, p["point_id"])
            os.makedirs(pdir, exist_ok=True)
            task = point_task(spec, p, pdir)
            if snap is not None:
                task["resume_from"] = _fork_for_point(
                    ramp_task, task, snap, pdir)
            log(f"sweep: point {p['point_id']}"
                + (" (warm)" if snap is not None else ""))
            ok, err, attempts = _attempt_point(
                task, pdir, spec["time_limit_s"], spec["retries"],
                log)
            if not ok:
                record_failure(p, pdir, err, attempts)
                continue
            manifest[p["point_id"]] = {
                "dir": pdir, "group": p["group"],
                "warm_started": snap is not None,
                "status": "ok", "attempts": attempts,
            }
            _write_manifest(out_dir, spec, manifest)
    _write_manifest(out_dir, spec, manifest)
    return manifest


def _fork_for_point(ramp_task, task, snap, pdir) -> str:
    """Fork the group snapshot into this point's variant archive (the
    base point resumes its own digest through the same seam, so every
    group member takes the identical code path).  Both configs are
    built through sweep/point.build_config from the TASK dicts the
    subprocesses actually ran — the digest the fork re-stamps is
    byte-for-byte the digest the resuming subprocess checks."""
    from shadow_tpu.ckpt.fork import fork_archive
    from shadow_tpu.sweep.point import build_config

    def cfg(t):
        c = build_config(t["yaml"], t["experimental"],
                         t["link_interval_ms"])
        if t.get("stop_time_ns"):
            c.general.stop_time_ns = int(t["stop_time_ns"])
        return c

    out = os.path.join(pdir, "warm.stck")
    fork_archive(snap, cfg(ramp_task), cfg(task), out)
    return out
