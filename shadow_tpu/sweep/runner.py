"""Campaign runner: execute the expanded run matrix in identity-safe
subprocesses, optionally warm-starting fork groups from a shared
post-ramp checkpoint.

Cold path (the default): one `python -m shadow_tpu.sweep.point`
subprocess per point, each with its own data directory and the spec's
per-point wall limit.

Warm path (`warm_start: {at_ms: N}`): points are grouped by their
fork-group key (sweep/spec.expand — everything but the fork-safe
axes).  Each group runs ONE ramp subprocess (the group's first point,
with a checkpoint scheduled at the warm-start instant), the snapshot
is forked per point via ckpt/fork.fork_archive (digest re-stamped for
the point's dctcp_k variant), and each point's subprocess RESUMES its
forked archive.  Warm-started variants share the ramp's bytes by
construction — the dataset records `warm_started` so nobody mistakes
a forked point for a cold run of the same config.

Determinism: subprocess stdout/stderr and wall times go to
`run.json`-adjacent logs, never into the dataset; the dataset reads
only the deterministic channels.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from shadow_tpu.sweep import spec as spec_mod


class PointFailure(RuntimeError):
    """A campaign point exited nonzero / timed out; the campaign
    fails loudly rather than aggregating a hole."""


def _point_env() -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _run_sub(task: dict, task_path: str, log_path: str,
             time_limit_s: float) -> None:
    with open(task_path, "w") as f:
        json.dump(task, f)
    with open(log_path, "w") as log:
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "shadow_tpu.sweep.point",
                 task_path],
                stdout=log, stderr=subprocess.STDOUT,
                env=_point_env(), timeout=time_limit_s,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))))
        except subprocess.TimeoutExpired:
            raise PointFailure(
                f"{os.path.basename(task_path)}: exceeded the "
                f"per-point time limit ({time_limit_s}s) — see "
                f"{log_path}") from None
    if proc.returncode != 0:
        tail = open(log_path).read()[-800:]
        raise PointFailure(
            f"{os.path.basename(task_path)}: exit "
            f"{proc.returncode}\n{tail}")


def point_task(spec: dict, point: dict, data_dir: str) -> dict:
    """THE task-dict recipe for one campaign point — run_campaign and
    bench's identity re-run both build through here, so the two can
    never drift into comparing differently-configured runs."""
    return {
        "yaml": spec_mod.point_yaml(spec, point),
        "data_dir": data_dir,
        "experimental": spec_mod.point_experimental(spec, point),
        "link_interval_ms": spec_mod.validate_spec(
            spec)["link_interval_ms"],
    }


# Sim-time headroom the warm-start ramp runs past its checkpoint
# instant: the snapshot lands at the first conservative-round boundary
# >= at_ms, so the ramp needs a little room after it — but nothing
# like the full scenario stop_time (the ramp is overhead; variants do
# the real running).
RAMP_HEADROOM_NS = 100_000_000


def _scenario_stop_ns(spec: dict) -> int:
    """The campaign's sim stop time in ns (spec.base or the netgen
    scenario default) — the warm-start gate needs it to refuse a ramp
    at/after the end."""
    from shadow_tpu.utils import units
    defaults = {"incast": "3s", "rpc_burst": "3s", "leaf_spine": "5s"}
    stop = spec["base"].get("stop_time",
                            defaults[spec["scenario"]])
    return units.parse_time_ns(stop)


def run_campaign(spec: dict, out_dir: str,
                 log=lambda msg: print(msg, file=sys.stderr)) -> dict:
    """Execute every point of `spec` under `out_dir` (one
    subdirectory per point, `<point_id>/`).  Returns the manifest
    {point_id: {dir, warm_started, group}} in matrix order.  Any
    point failure raises PointFailure — no partial datasets."""
    spec = spec_mod.validate_spec(spec)
    points = spec_mod.expand(spec)
    os.makedirs(out_dir, exist_ok=True)
    warm = spec["warm_start"]
    manifest: dict = {}
    groups: dict = {}
    for p in points:
        groups.setdefault(p["group"], []).append(p)

    if warm is not None:
        ramp_ns = warm["at_ms"] * 1_000_000
        stop_ns = _scenario_stop_ns(spec)
        if ramp_ns >= stop_ns:
            raise spec_mod.SpecError(
                f"warm_start.at_ms ({warm['at_ms']} ms) is not "
                f"before the scenario stop_time "
                f"({stop_ns // 1_000_000} ms)")

    for gname, gpoints in groups.items():
        snap = None
        ramp_task = None
        if warm is not None:
            # ONE ramp per fork group: the group's first point's
            # scenario config with the group-base experimental
            # values, checkpointed at the warm-start boundary and
            # STOPPED just past it (the full stop_time is the
            # variants' job; stop_time is fork-safe, so the truncated
            # ramp archive forks to full-length variants).
            ramp_ns = warm["at_ms"] * 1_000_000
            ramp_dir = os.path.join(out_dir, f"ramp.{gname}")
            os.makedirs(ramp_dir, exist_ok=True)
            log(f"sweep: ramp [{gname}] -> checkpoint at "
                f"{warm['at_ms']} ms")
            ramp_task = point_task(spec, gpoints[0], ramp_dir)
            ramp_task["checkpoint"] = {"at_ns": [ramp_ns],
                                       "directory": ramp_dir}
            ramp_task["stop_time_ns"] = min(
                _scenario_stop_ns(spec), ramp_ns + RAMP_HEADROOM_NS)
            _run_sub(ramp_task,
                     os.path.join(ramp_dir, "task.json"),
                     os.path.join(ramp_dir, "log.txt"),
                     spec["time_limit_s"])
            snap = os.path.join(ramp_dir, f"ckpt-{ramp_ns}.stck")
            if not os.path.exists(snap):
                raise PointFailure(
                    f"ramp [{gname}] wrote no snapshot at "
                    f"{warm['at_ms']} ms (boundary never reached "
                    f"before stop_time?)")

        for p in gpoints:
            pdir = os.path.join(out_dir, p["point_id"])
            os.makedirs(pdir, exist_ok=True)
            task = point_task(spec, p, pdir)
            if snap is not None:
                task["resume_from"] = _fork_for_point(
                    ramp_task, task, snap, pdir)
            log(f"sweep: point {p['point_id']}"
                + (" (warm)" if snap is not None else ""))
            _run_sub(task, os.path.join(pdir, "task.json"),
                     os.path.join(pdir, "log.txt"),
                     spec["time_limit_s"])
            manifest[p["point_id"]] = {
                "dir": pdir, "group": p["group"],
                "warm_started": snap is not None,
            }
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump({"spec": spec, "points": manifest}, f,
                  sort_keys=True, indent=1)
    return manifest


def _fork_for_point(ramp_task, task, snap, pdir) -> str:
    """Fork the group snapshot into this point's variant archive (the
    base point resumes its own digest through the same seam, so every
    group member takes the identical code path).  Both configs are
    built through sweep/point.build_config from the TASK dicts the
    subprocesses actually ran — the digest the fork re-stamps is
    byte-for-byte the digest the resuming subprocess checks."""
    from shadow_tpu.ckpt.fork import fork_archive
    from shadow_tpu.sweep.point import build_config

    def cfg(t):
        c = build_config(t["yaml"], t["experimental"],
                         t["link_interval_ms"])
        if t.get("stop_time_ns"):
            c.general.stop_time_ns = int(t["stop_time_ns"])
        return c

    out = os.path.join(pdir, "warm.stck")
    fork_archive(snap, cfg(ramp_task), cfg(task), out)
    return out
