"""One campaign point, executed in its own subprocess.

`python -m shadow_tpu.sweep.point TASK.json` — the runner writes the
task file and collects the point's data directory afterward.  A fresh
interpreter per point is the identity-safe execution rung bench.py's
sharded suite established: no JAX/engine state, compile caches, or
module-level counters can leak between points, so a campaign's bytes
depend only on its spec.

Task file keys:
    yaml          scenario config text (sweep/spec.point_yaml)
    data_dir      the point's output directory
    experimental  {option: value} overrides (the dctcp_k axis)
    link_interval_ms   fabric sampling grid
    stop_time_ns  optional stop override (the truncated ramp)
    checkpoint    optional {at_ns: [..], directory}: write a ramp
                  snapshot (the warm-start base run)
    resume_from   optional snapshot path: resume instead of starting
                  cold (a forked variant archive)

The point always runs with the fabric observatory AND sim-netstat on —
the channels ARE the dataset.  On success it writes `topo.json`
(dense graph nodes/edges + host->node map — the surrogate's path
derivation input) and `point.json` (summary counters + the fabric
conservation verdict) next to the channels, then exits 0; any
failure exits nonzero with the error on stderr.
"""

from __future__ import annotations

import json
import os
import sys


def build_config(yaml_text: str, experimental: dict | None,
                 link_interval_ms: int):
    """The ONE config shape every campaign point runs under — shared
    with the runner's fork-variant builder, so the digest the fork
    re-stamps is byte-for-byte the digest the resuming subprocess
    checks.  Channel knobs are digest-semantic (they shape channel
    bytes); a second copy of this recipe would let the two drift."""
    from shadow_tpu.core.config import ConfigOptions

    config = ConfigOptions.from_yaml_text(yaml_text)
    config.general.progress = False
    config.experimental.sim_fabricstat = "on"
    config.experimental.sim_netstat = "on"
    config.experimental.fabricstat_interval_ns = \
        int(link_interval_ms) * 1_000_000
    config.experimental.netstat_interval_ns = \
        config.experimental.fabricstat_interval_ns
    for k, v in (experimental or {}).items():
        if not hasattr(config.experimental, k):
            raise ValueError(f"unknown experimental override {k!r}")
        setattr(config.experimental, k, v)
    return config


def run_point(task: dict) -> int:
    from shadow_tpu.core.config import CheckpointConfig
    from shadow_tpu.core.manager import (resume_simulation,
                                         run_simulation)

    config = build_config(task["yaml"], task.get("experimental"),
                          task.get("link_interval_ms", 0))
    data_dir = task["data_dir"]
    config.general.data_directory = data_dir
    if task.get("stop_time_ns"):
        # The warm-start ramp stops just past its checkpoint instant
        # (runner.RAMP_HEADROOM_NS) — stop_time is fork-safe, so the
        # truncated archive forks to full-length variants.
        config.general.stop_time_ns = int(task["stop_time_ns"])
    if task.get("checkpoint"):
        config.checkpoint = CheckpointConfig(
            at_ns=[int(t) for t in task["checkpoint"]["at_ns"]],
            directory=task["checkpoint"]["directory"])
    if task.get("resume_from"):
        manager, summary = resume_simulation(
            config, task["resume_from"], write_data=True)
    else:
        manager, summary = run_simulation(config, write_data=True)
    if not summary.ok:
        print(f"point failed: {summary.plugin_errors[:3]}",
              file=sys.stderr)
        return 1

    graph = manager.graph
    topo = {
        "nodes": [{"index": n.index,
                   "bw_down": n.bandwidth_down_bits or 0,
                   "bw_up": n.bandwidth_up_bits or 0}
                  for n in graph.nodes],
        "edges": sorted(
            [e.source, e.target, e.latency_ns]
            for e in graph.edges),
        "hosts": {str(h.id): h.node_index for h in manager.hosts},
        # IP -> host id: FCT records name the peer by IP; the
        # surrogate featurizer resolves the sender's node through
        # this map.
        "host_ips": {str(h.ip): h.id for h in manager.hosts},
    }
    with open(os.path.join(data_dir, "topo.json"), "w") as f:
        json.dump(topo, f, sort_keys=True, separators=(",", ":"))

    fabric = manager.fabric_summary(summary.busy_end_ns)
    point = {
        "ok": True,
        "packets_sent": summary.packets_sent,
        "busy_end_ns": summary.busy_end_ns,
        "conservation": fabric["conservation"],
        "marked_pkts": fabric["marked_pkts"],
        "peak_queue_depth": fabric["peak_queue_depth"],
        "flows": fabric.get("fct", {}).get("flows", 0),
        "resumed": bool(task.get("resume_from")),
    }
    with open(os.path.join(data_dir, "point.json"), "w") as f:
        json.dump(point, f, sort_keys=True, indent=1)
    if fabric["conservation"] != "ok":
        print(f"point conservation violated: "
              f"{fabric['conservation']}", file=sys.stderr)
        return 2
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m shadow_tpu.sweep.point TASK.json",
              file=sys.stderr)
        return 2
    from shadow_tpu.utils.platform import honor_platform_env
    honor_platform_env()
    with open(argv[0]) as f:
        task = json.load(f)
    return run_point(task)


if __name__ == "__main__":
    sys.exit(main())
