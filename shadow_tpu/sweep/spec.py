"""Campaign spec: a declarative axes-product description of a
tail-latency sweep, expanded into a DETERMINISTIC run matrix.

A spec is a plain dict (YAML-friendly; tools/sweep reads either):

    name: incast-k-sweep          # required, [a-z0-9-]+
    scenario: incast              # incast | rpc_burst | leaf_spine
    seeds: [17]                   # optional; default: the scenario's
    base: {fan_in: 8, nbytes: 200000, stop_time: "2s"}   # optional
    axes:                         # optional; each value is a list
      load: [0.5, 1.0]            # scales offered bytes (nbytes)
      fan_in: [4, 8, 16]          # fan-in width (see _AXES)
      dctcp_k: [10, 20]           # marking threshold K, packets
      cc: [reno, dctcp]           # congestion controller
      size_law: [fixed, pareto]   # rpc_burst only
    time_limit_s: 120             # per-point subprocess wall limit
    warm_start: {at_ms: 500}      # optional: fork-from-ramp points
    link_interval_ms: 0           # fabric sampling grid (0 = every
                                  # round)

Expansion is pure: axes iterate in sorted-name order, values in spec
order, seeds outermost — so the same spec ALWAYS yields the same
ordered point list, and with it the same dataset bytes (the two-run
byte-identity gate in tests/test_sweep.py).  Every invalid axis,
value, or scenario/axis pairing is refused at expansion time with the
offending key named — a campaign must never discover a bad point an
hour in.
"""

from __future__ import annotations

import re

# Axis registry: name -> (validator, scenarios it applies to).
# `load` multiplies the scenario's offered bytes; `fan_in` maps to the
# scenario's width knob (incast fan_in / rpc_burst n_clients /
# leaf_spine hosts_per_leaf); `n_leaf` is the leaf-spine fabric SIZE
# (the held-out-fabric validation axis); `dctcp_k` sets
# experimental.dctcp_k_pkts with dctcp_k_bytes scaled at MTU (1500 B)
# per packet — the fork-safe warm-start axis.
_ALL = ("incast", "rpc_burst", "leaf_spine")


def _pos_num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and v > 0


def _pos_int(v):
    return isinstance(v, int) and not isinstance(v, bool) and v > 0


AXES = {
    "load": (_pos_num, _ALL),
    "fan_in": (_pos_int, _ALL),
    "n_leaf": (lambda v: _pos_int(v) and v >= 2, ("leaf_spine",)),
    "dctcp_k": (_pos_int, _ALL),
    "cc": (lambda v: v in ("reno", "dctcp"), _ALL),
    "size_law": (lambda v: v in ("fixed", "pareto", "lognormal"),
                 ("rpc_burst",)),
}

# The fork-safe axes (ckpt/fork.py FORK_SAFE_*): points differing only
# here share a warm-start ramp; any other axis forces a cold start.
FORK_SAFE_AXES = frozenset({"dctcp_k"})

# Per-scenario defaults mirroring the netgen signatures: seed, the
# offered-bytes base the `load` axis scales, and the fan-in WIDTH the
# scenario runs when neither axes nor base set one — point_features
# must record the width the simulator actually uses, never 0.
SCENARIO_DEFAULTS = {
    "incast": {"seed": 17, "nbytes": 500_000, "width": 8,
               "n_leaf": 0},
    "rpc_burst": {"seed": 31, "nbytes": 20_000, "width": 8,
                  "n_leaf": 0},
    "leaf_spine": {"seed": 23, "nbytes": 1_000_000, "width": 4,
                   "n_leaf": 4},
}

_SPEC_KEYS = {"name", "scenario", "seeds", "base", "axes",
              "time_limit_s", "warm_start", "link_interval_ms",
              "retries", "max_failed_points"}


class SpecError(ValueError):
    """Any campaign-spec validation failure, with the offending key
    named."""


def validate_spec(spec: dict) -> dict:
    """Normalized copy of `spec` (defaults filled) or SpecError."""
    if not isinstance(spec, dict):
        raise SpecError("campaign spec must be a mapping")
    unknown = set(spec) - _SPEC_KEYS
    if unknown:
        raise SpecError(f"unknown spec key(s) {sorted(unknown)}")
    name = spec.get("name")
    if not isinstance(name, str) or not re.fullmatch(r"[a-z0-9-]+",
                                                     name):
        raise SpecError(f"spec.name must match [a-z0-9-]+, got "
                        f"{name!r}")
    scenario = spec.get("scenario")
    if scenario not in SCENARIO_DEFAULTS:
        raise SpecError(f"spec.scenario must be one of "
                        f"{sorted(SCENARIO_DEFAULTS)}, got "
                        f"{scenario!r}")
    seeds = spec.get("seeds", [SCENARIO_DEFAULTS[scenario]["seed"]])
    if not isinstance(seeds, list) or not seeds \
            or not all(_pos_int(s) for s in seeds):
        raise SpecError(f"spec.seeds must be a non-empty list of "
                        f"positive ints, got {seeds!r}")
    base = spec.get("base", {})
    if not isinstance(base, dict):
        raise SpecError("spec.base must be a mapping of scenario "
                        "keyword arguments")
    axes = spec.get("axes", {})
    if not isinstance(axes, dict):
        raise SpecError("spec.axes must be a mapping axis -> [values]")
    for axis, values in axes.items():
        if axis not in AXES:
            raise SpecError(f"unknown axis {axis!r}; known: "
                            f"{sorted(AXES)}")
        check, scenarios = AXES[axis]
        if scenario not in scenarios:
            raise SpecError(f"axis {axis!r} does not apply to "
                            f"scenario {scenario!r} (only "
                            f"{list(scenarios)})")
        if not isinstance(values, list) or not values:
            raise SpecError(f"axis {axis!r} needs a non-empty value "
                            f"list")
        bad = [v for v in values if not check(v)]
        if bad:
            raise SpecError(f"axis {axis!r}: invalid value(s) {bad}")
        if len(set(map(repr, values))) != len(values):
            raise SpecError(f"axis {axis!r}: duplicate values")
    tl = spec.get("time_limit_s", 300)
    if not _pos_num(tl):
        raise SpecError(f"spec.time_limit_s must be > 0, got {tl!r}")
    warm = spec.get("warm_start")
    if warm is not None:
        if not isinstance(warm, dict) or set(warm) != {"at_ms"} \
                or not _pos_int(warm["at_ms"]):
            raise SpecError("spec.warm_start must be {at_ms: "
                            "<positive int>}")
    li = spec.get("link_interval_ms", 0)
    if not isinstance(li, int) or isinstance(li, bool) or li < 0:
        raise SpecError(f"spec.link_interval_ms must be an int >= 0, "
                        f"got {li!r}")
    # Self-healing fleet knobs (docs/ROBUSTNESS.md "Self-healing
    # sweeps"): per-point retry count with bounded backoff, and how
    # many points may FAIL outright before the campaign aborts —
    # failed points land in the dataset's metadata, never as holes.
    retries = spec.get("retries", 1)
    if not isinstance(retries, int) or isinstance(retries, bool) \
            or retries < 0:
        raise SpecError(f"spec.retries must be an int >= 0, got "
                        f"{retries!r}")
    mfp = spec.get("max_failed_points", 0)
    if not isinstance(mfp, int) or isinstance(mfp, bool) or mfp < 0:
        raise SpecError(f"spec.max_failed_points must be an int >= 0, "
                        f"got {mfp!r}")
    return {"name": name, "scenario": scenario, "seeds": list(seeds),
            "base": dict(base), "axes": {k: list(v) for k, v
                                         in sorted(axes.items())},
            "time_limit_s": tl, "warm_start": warm,
            "link_interval_ms": li, "retries": retries,
            "max_failed_points": mfp}


def expand(spec: dict) -> list[dict]:
    """The deterministic run matrix: one dict per point, ordered
    seeds-outermost then axes in sorted-name order (values in spec
    order).  Each point carries its stable `point_id`, the axis
    assignment, the seed, and its warm-start GROUP key (points
    differing only in fork-safe axes share a ramp)."""
    spec = validate_spec(spec)
    axes = spec["axes"]
    names = sorted(axes)
    points: list[dict] = []
    combos: list[dict] = [{}]
    for axis in names:
        combos = [dict(c, **{axis: v}) for c in combos
                  for v in axes[axis]]
    for seed in spec["seeds"]:
        for combo in combos:
            ident = [f"s{seed}"] + [
                f"{a}-{str(combo[a]).replace('.', 'p')}"
                for a in names]
            group = [f"s{seed}"] + [
                f"{a}-{str(combo[a]).replace('.', 'p')}"
                for a in names if a not in FORK_SAFE_AXES]
            points.append({
                "point_id": f"p{len(points):04d}." + ".".join(ident),
                "seed": seed,
                "axes": dict(combo),
                "group": ".".join(group) or "all",
            })
    return points


def point_features(spec: dict, point: dict) -> dict:
    """The config-feature dict the dataset records per point (and the
    surrogate featurizer consumes): every axis resolved to its
    effective value, defaults filled — sorted-key JSON of this is part
    of the dataset bytes."""
    spec = validate_spec(spec)
    ax = point["axes"]
    base = spec["base"]
    nbytes = base.get("nbytes",
                      SCENARIO_DEFAULTS[spec["scenario"]]["nbytes"])
    defaults = SCENARIO_DEFAULTS[spec["scenario"]]
    width_base = base.get("fan_in", base.get("hosts_per_leaf",
                                             base.get("n_clients",
                                                      0)))
    return {
        "scenario": spec["scenario"],
        "seed": point["seed"],
        "load": float(ax.get("load", 1.0)),
        "nbytes": int(round(nbytes * float(ax.get("load", 1.0)))),
        "fan_in": int(ax.get("fan_in",
                             width_base or defaults["width"])),
        "n_leaf": int(ax.get("n_leaf", base.get("n_leaf",
                                                defaults["n_leaf"]))),
        "dctcp_k": int(ax.get("dctcp_k", 20)),
        "cc": str(ax.get("cc", (base.get("tcp") or {}).get("cc",
                                                           "reno"))),
        "size_law": str(ax.get("size_law",
                               base.get("size_law") or "fixed")),
    }


def point_yaml(spec: dict, point: dict) -> str:
    """The point's full simulation config YAML (netgen scenario text;
    experimental overrides ride separately in point_experimental so
    the warm-start fork sees a clean base/variant split)."""
    from shadow_tpu.tools import netgen
    spec = validate_spec(spec)
    feats = point_features(spec, point)
    base = dict(spec["base"])
    base.pop("tcp", None)
    base["seed"] = point["seed"]
    base["nbytes"] = feats["nbytes"]
    tcp = ({"cc": "dctcp", "ecn": "on"} if feats["cc"] == "dctcp"
           else (spec["base"].get("tcp") or None))
    scenario = spec["scenario"]
    if scenario == "incast":
        base.pop("fan_in", None)
        return netgen.incast_yaml(feats["fan_in"], tcp=tcp, **base)
    if scenario == "rpc_burst":
        law = feats["size_law"]
        base["size_law"] = None if law == "fixed" else law
        base["n_clients"] = feats["fan_in"]
        return netgen.rpc_burst_yaml(tcp=tcp, **base)
    base["hosts_per_leaf"] = feats["fan_in"]
    base["n_leaf"] = feats["n_leaf"]
    return netgen.leaf_spine_yaml(tcp=tcp, **base)


def point_experimental(spec: dict, point: dict) -> dict:
    """Experimental-section overrides for the point (applied on top
    of the scenario YAML by the point subprocess AND by the fork
    variant builder): the DCTCP-K axis, packets leg as given, bytes
    leg scaled at one MTU per packet."""
    k = int(point["axes"].get("dctcp_k", 20))
    return {"dctcp_k_pkts": k, "dctcp_k_bytes": k * 1500}
