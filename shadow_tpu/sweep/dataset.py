"""Campaign dataset: every point's deterministic channels pulled into
ONE canonical, byte-stable artifact, plus tail-curve tables.

File layout (`<name>.swds`)::

    DS_HDR    magic "SWDS", version, meta/flows/links byte lengths
    meta      sorted-key compact JSON: the normalized spec, the
              ordered per-point table (config features, topology,
              counts, conservation verdicts, tail-curve tables)
    flows     per point, concatenated FCT_REC records — the RECEIVER
              vantage rows (trace/fabricstat.receiver_rows: one row
              per flow), sorted by full field tuple
    links     per point, concatenated FB_REC records (the per-link
              queue series, already canonically ordered)

Everything that reaches the bytes is either a deterministic channel
or a sorted-key JSON of spec-derived values, so the same spec always
yields the same file (tests/test_sweep.py runs a 2-point campaign
twice and byte-compares).  Wall times, logs, and subprocess output
never enter.

Aggregation is fail-closed: a missing channel, a conservation
violation, a dataset/channel flow-count mismatch, or a quantile
inversion (p50 > p99 etc.) raises DatasetError — `bench[sweep-*]`
refuses to record on exactly these errors.
"""

from __future__ import annotations

import json
import os
import struct

from shadow_tpu.sweep import spec as spec_mod
from shadow_tpu.trace.events import (FCT_REC, FCT_REC_BYTES,
                                     FB_REC_BYTES, iter_fct_records,
                                     split_fabric)
from shadow_tpu.trace.fabricstat import percentile, receiver_rows

DS_MAGIC = 0x53445753  # "SWDS"
DS_VERSION = 1
DS_HDR = struct.Struct("<IIQQQ")
DS_HDR_BYTES = 32
assert DS_HDR.size == DS_HDR_BYTES


class DatasetError(RuntimeError):
    """Any aggregation failure (missing channel, conservation or
    identity violation) — campaigns fail loudly, never silently
    under-collect."""


def _point_quantiles(durs: list) -> dict:
    durs = sorted(durs)
    q = {"p50_ns": percentile(durs, 500),
         "p99_ns": percentile(durs, 990),
         "p999_ns": percentile(durs, 999)}
    if not (q["p50_ns"] <= q["p99_ns"] <= q["p999_ns"]):
        raise DatasetError(f"quantile inversion: {q}")
    return q


def tail_curves(points_meta: list) -> list:
    """p50/p99/p999 FCT vs offered load, one curve per combination of
    every non-load feature (the spec's other axes + seed), ordered by
    curve key then load.  `p99_monotone_frac` is the fraction of
    adjacent load steps where p99 does not decrease — recorded
    honestly (queueing says it should mostly rise; the number says
    whether it did)."""
    curves: dict = {}
    for pm in points_meta:
        f = pm["features"]
        key = json.dumps(
            {k: v for k, v in sorted(f.items()) if k != "load"
             and k != "nbytes"},
            sort_keys=True)
        curves.setdefault(key, []).append(
            (f["load"],
             {"load": f["load"], "flows": pm["counts"]["flows"],
              **pm["quantiles"]}))
    out = []
    for key in sorted(curves):
        rows = [r for _load, r in sorted(curves[key],
                                         key=lambda lr: lr[0])]
        steps = [(a["p99_ns"], b["p99_ns"])
                 for a, b in zip(rows, rows[1:])]
        frac = (sum(1 for a, b in steps if b >= a) / len(steps)
                if steps else 1.0)
        out.append({"key": json.loads(key), "rows": rows,
                    "p99_monotone_frac": round(frac, 4)})
    return out


def aggregate(spec: dict, out_dir: str) -> "Dataset":
    """Read every point directory under `out_dir` (the runner's
    manifest order == the spec's matrix order) into a Dataset."""
    spec = spec_mod.validate_spec(spec)
    points = spec_mod.expand(spec)
    manifest_path = os.path.join(out_dir, "manifest.json")
    warm: dict = {}
    man_points: dict = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            man_points = json.load(f)["points"]
            warm = {pid: ent.get("warm_started", False)
                    for pid, ent in man_points.items()}
    metas: list = []
    flow_blobs: list = []
    link_blobs: list = []
    failed_points: list = []
    for p in points:
        # Self-healing fleet (docs/ROBUSTNESS.md): a point the runner
        # recorded as FAILED is listed honestly in the dataset
        # metadata — a partial-but-honest dataset, never a silent
        # hole (an unrecorded missing point still fails below).
        ent = man_points.get(p["point_id"], {})
        if ent.get("status") == "failed":
            failed_points.append({
                "point_id": p["point_id"],
                "seed": p["seed"],
                "axes": p["axes"],
                "error": ent.get("error", ""),
            })
            continue
        pdir = os.path.join(out_dir, p["point_id"])
        fab_path = os.path.join(pdir, "fabric-sim.bin")
        pj_path = os.path.join(pdir, "point.json")
        topo_path = os.path.join(pdir, "topo.json")
        missing = [os.path.basename(f) for f in
                   (fab_path, pj_path, topo_path)
                   if not os.path.exists(f)]
        if missing:
            raise DatasetError(f"{p['point_id']}: missing "
                               f"{', '.join(missing)} under {pdir}")
        with open(pj_path) as f:
            pj = json.load(f)
        if pj.get("conservation") != "ok":
            raise DatasetError(f"{p['point_id']}: fabric conservation "
                               f"violated: {pj.get('conservation')}")
        with open(fab_path, "rb") as f:
            fb_bytes, fct_bytes = split_fabric(f.read())
        endpoint_rows = list(iter_fct_records(fct_bytes))
        flows = sorted(receiver_rows(endpoint_rows))
        # THE aggregator conservation gate: the dataset's flow count
        # must equal the FCT channel's receiver-vantage row count AND
        # the summary the point subprocess recorded from live state.
        if len(flows) != pj.get("flows", -1):
            raise DatasetError(
                f"{p['point_id']}: dataset flow count {len(flows)} "
                f"!= point summary {pj.get('flows')}")
        with open(topo_path) as f:
            topo = json.load(f)
        durs = [r[1] - r[0] for r in flows]
        if not durs:
            raise DatasetError(f"{p['point_id']}: no flows carried "
                               f"payload — nothing to learn from")
        metas.append({
            "point_id": p["point_id"],
            "seed": p["seed"],
            "axes": p["axes"],
            "features": spec_mod.point_features(spec, p),
            "topo": topo,
            "counts": {"flows": len(flows),
                       "endpoints": len(endpoint_rows),
                       "links": len(fb_bytes) // FB_REC_BYTES},
            "quantiles": _point_quantiles(durs),
            "marked_pkts": pj.get("marked_pkts", 0),
            "peak_queue_depth": pj.get("peak_queue_depth", 0),
            "warm_started": warm.get(p["point_id"], False),
        })
        flow_blobs.append(b"".join(FCT_REC.pack(*r) for r in flows))
        link_blobs.append(fb_bytes)
    if not metas:
        raise DatasetError(
            "every campaign point failed — nothing to aggregate "
            f"({len(failed_points)} failures recorded)")
    meta = {
        "version": DS_VERSION,
        "name": spec["name"],
        "spec": spec,
        "points": metas,
        "failed_points": failed_points,
        "tail_curves": tail_curves(metas),
    }
    return Dataset(meta, flow_blobs, link_blobs)


class Dataset:
    """One aggregated campaign: `meta` (the JSON dict above) plus the
    per-point packed record blobs, in matrix order."""

    def __init__(self, meta: dict, flow_blobs: list,
                 link_blobs: list):
        self.meta = meta
        self.flow_blobs = flow_blobs
        self.link_blobs = link_blobs

    def to_bytes(self) -> bytes:
        mb = json.dumps(self.meta, sort_keys=True,
                        separators=(",", ":")).encode()
        fb = b"".join(self.flow_blobs)
        lb = b"".join(self.link_blobs)
        return DS_HDR.pack(DS_MAGIC, DS_VERSION, len(mb), len(fb),
                           len(lb)) + mb + fb + lb

    def write(self, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.to_bytes())

    def point_flows(self, idx: int):
        """Point idx's flow rows as FCT field tuples."""
        return list(iter_fct_records(self.flow_blobs[idx]))


def load(path: str) -> Dataset:
    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) < DS_HDR_BYTES:
        raise DatasetError(f"{path}: shorter than a dataset header")
    magic, version, mlen, flen, llen = DS_HDR.unpack_from(buf, 0)
    if magic != DS_MAGIC:
        raise DatasetError(f"{path}: not a sweep dataset "
                           f"(magic {magic:#x})")
    if version != DS_VERSION:
        raise DatasetError(f"{path}: dataset version {version} != "
                           f"supported {DS_VERSION}")
    if len(buf) != DS_HDR_BYTES + mlen + flen + llen:
        raise DatasetError(f"{path}: truncated dataset")
    meta = json.loads(buf[DS_HDR_BYTES:DS_HDR_BYTES + mlen].decode())
    flows = buf[DS_HDR_BYTES + mlen:DS_HDR_BYTES + mlen + flen]
    links = buf[DS_HDR_BYTES + mlen + flen:]
    flow_blobs, link_blobs = [], []
    fo = lo = 0
    for pm in meta["points"]:
        fn = pm["counts"]["flows"] * FCT_REC_BYTES
        ln = pm["counts"]["links"] * FB_REC_BYTES
        flow_blobs.append(flows[fo:fo + fn])
        link_blobs.append(links[lo:lo + ln])
        fo += fn
        lo += ln
    if fo != len(flows) or lo != len(links):
        raise DatasetError(f"{path}: record sections disagree with "
                           f"the meta counts")
    return Dataset(meta, flow_blobs, link_blobs)
