"""Scalar (CPU) cross-host packet propagation.

The reference's `Worker::send_packet` hot path (src/main/core/worker.rs:
324-397): resolve destination, loss decision, latency lookup, clamp
delivery into the next round, push to the destination queue. This scalar
backend serves the serial and threaded schedulers and is the semantic
reference for the batched TPU backend (ops/propagate.py) — the two must
produce byte-identical traces, which is why every decision here is pure
integer math on the same matrices and the same counter-based RNG the
kernel uses.
"""

from __future__ import annotations

import threading

from shadow_tpu.core.event import Event, KIND_PACKET
from shadow_tpu.core.rng import (STREAM_PACKET_LOSS, mix_key, threefry2x32_py)
from shadow_tpu.core.simtime import TIME_NEVER
from shadow_tpu.net import packet as pkt


class ScalarPropagator:
    def __init__(self, hosts, dns, latency_ns, loss_thresholds, seed: int,
                 bootstrap_end_ns: int, threaded: bool = False,
                 runahead=None):
        self.hosts = hosts
        self.dns = dns
        self.latency = latency_ns          # (V,V) int64 ndarray
        self.thresholds = loss_thresholds  # (V,V) int64 ndarray in [0, 2^32]
        self.k0, self.k1 = mix_key(seed, STREAM_PACKET_LOSS)
        self.bootstrap_end = bootstrap_end_ns
        self.window_end = 0
        self.min_inflight = None
        self.runahead = runahead  # dynamic-runahead feedback (runahead.rs:61)
        self._threaded = threaded
        self.engine = None  # native plane engine (set by the Manager)
        if threaded:
            self._min_lock = threading.Lock()

    def begin_round(self, window_start: int, window_end: int) -> None:
        self.window_end = window_end
        self.min_inflight = None

    def finish_round(self):
        m = self.min_inflight
        eng = self.engine
        if eng is not None and eng.round_size():
            # Engine-batched sends (engine-backed thread_per_core):
            # the C++ propagation twin — bit-identical loss/latency
            # math — delivers into engine inboxes and exports packets
            # bound for object-path hosts.
            from shadow_tpu.ops.propagate import deliver_engine_exports
            _nf, md, ml, exports = eng.finish_round(self.window_end)
            if exports is not None:
                deliver_engine_exports(self.hosts, exports)
            if self.runahead is not None and ml < TIME_NEVER:
                self.runahead.update_lowest_used_latency(ml)
            if md < TIME_NEVER and (m is None or md < m):
                m = md
        return m

    def send(self, src_host, packet) -> None:
        if src_host.link_down:
            # NIC link down (docs/CHECKPOINT.md faults): the send dies
            # at the egress instant, BEFORE the event-seq draw — the
            # same position as the no-route drop, matching the C++
            # twin (netplane.cpp device_push).
            src_host.trace_drop(packet, "link-down")
            return
        now = src_host.now()
        dst_id = self.dns.host_id_for_ip(packet.dst_ip)
        if dst_id is None:
            src_host.trace_drop(packet, "no-route")
            return
        dst_host = self.hosts[dst_id]

        # Event sequence is consumed *before* the reachability and loss
        # decisions so the numbering is identical on the batched path
        # (where both are decided later, on device).
        seq = src_host.next_event_seq()

        latency = int(self.latency[src_host.node_index, dst_host.node_index])
        if latency >= TIME_NEVER:
            src_host.trace_drop(packet, "unreachable")
            return

        threshold = int(self.thresholds[src_host.node_index,
                                        dst_host.node_index])
        if threshold > 0 and now >= self.bootstrap_end \
                and not packet.is_empty_control():
            bits, _ = threefry2x32_py(self.k0, self.k1,
                                      packet.src_host_id & 0xFFFFFFFF,
                                      packet.seq & 0xFFFFFFFF)
            if bits < threshold:
                packet.record(pkt.ST_INET_DROPPED)
                src_host.trace_drop(packet, "inet-loss")
                return

        # Conservative clamp (worker.rs:380-384): delivery may never land
        # inside the current window — the destination may already have
        # executed past `now + latency`.
        deliver = now + latency
        if deliver < self.window_end:
            deliver = self.window_end
        if dst_host.plane is not None:
            # Mixed planes: object-path origin, engine destination.
            from shadow_tpu.ops.propagate import deliver_to_host
            deliver_to_host(dst_host, deliver, src_host.id, seq, packet)
        else:
            packet.arrival_time = deliver
            event = Event(deliver, KIND_PACKET, src_host.id, seq, packet)
            dst_host.deliver_packet_event(event)  # inbox: thread-safe

        if self._threaded:
            with self._min_lock:
                if self.min_inflight is None or deliver < self.min_inflight:
                    self.min_inflight = deliver
                if self.runahead is not None:
                    self.runahead.update_lowest_used_latency(latency)
        else:
            if self.min_inflight is None or deliver < self.min_inflight:
                self.min_inflight = deliver
            if self.runahead is not None:
                self.runahead.update_lowest_used_latency(latency)
