"""Manager: build the simulation and run the conservative round loop.

Ref: src/main/core/manager.rs (build + round loop, :228,:415-501) and
controller.rs:87-113 (window computation). One class covers both here —
multi-manager was an acknowledged TODO in the reference and our
multi-device story lives in the scheduler instead.

The loop is the PDES heart: pick the global minimum next-event time,
open a window [start, start + runahead], let every host execute its
events inside the window in parallel, exchange the round's packets, and
reduce the next window start. The *scheduler* decides how hosts execute
(serial / thread pool) and the *propagator* decides how packets cross
hosts (scalar CPU / batched TPU kernel).
"""

from __future__ import annotations

import json
import os
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from shadow_tpu.core import simtime
from shadow_tpu.core.config import ConfigOptions
from shadow_tpu.core.propagate_scalar import ScalarPropagator
from shadow_tpu.core.rng import loss_threshold_u32
from shadow_tpu.host import apps as app_registry
from shadow_tpu.host.host import Host
from shadow_tpu.host.process import Process
from shadow_tpu.host.syscalls import SyscallHandler
from shadow_tpu.net.dns import Dns


@dataclass
class SimSummary:
    end_time_ns: int = 0
    busy_end_ns: int = 0  # window end of the last round that ran events
    rounds: int = 0
    span_rounds: int = 0  # of which: served inside C++/device spans
    events: int = 0
    packets_sent: int = 0
    packets_recv: int = 0
    packets_dropped: int = 0
    syscalls: int = 0
    plugin_errors: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.plugin_errors


class Runahead:
    """Round width (ref: src/main/core/runahead.rs:14-117): the smallest
    latency any packet can experience bounds how far hosts may run
    without hearing from each other. A config value overrides; dynamic
    mode lowers it as smaller latencies are actually used."""

    def __init__(self, config_ns: int | None, graph_min_ns: int,
                 dynamic: bool):
        self._value = config_ns if config_ns is not None else graph_min_ns
        self._value = max(int(self._value), 1)
        self._dynamic = dynamic

    def get(self) -> int:
        return self._value

    @property
    def dynamic(self) -> bool:
        return self._dynamic

    def update_lowest_used_latency(self, latency_ns: int) -> None:
        if self._dynamic and 0 < latency_ns < self._value:
            self._value = latency_ns

    def sync_from_span(self, value_ns: int) -> None:
        """Adopt the (only ever lowered) width the engine's span loop
        computed with the same update rule."""
        if 0 < value_ns < self._value:
            self._value = int(value_ns)


# Sentinel: a device span that legitimately made no progress (window
# boundary), distinct from a failed/aborted one.
ZERO_PROGRESS = object()


class SpawnTask:
    """Picklable process-spawn task (one per configured process).

    Everything it needs rides the host (dns, syscall handlers, strace
    mode, the engine plane) or its own ProcessConfig, so a PENDING
    spawn survives a checkpoint: the pickled event queue carries this
    object, not a closure over the Manager (docs/CHECKPOINT.md)."""

    __slots__ = ("pcfg", "index")

    def __init__(self, pcfg, index: int):
        self.pcfg = pcfg
        self.index = index

    def __call__(self, h) -> None:
        pcfg = self.pcfg
        strace_mode = h.strace_mode
        # Engine-resident tgen apps: when the host lives on the
        # native plane and nothing needs the Python process
        # machinery (no strace), the whole app/syscall/TCP path
        # runs in C++ with a byte-identical packet trace
        # (host/engine_app.py) — including default-disposition
        # signal delivery for shutdown_time configs.
        if h.plane is not None and strace_mode is None:
            from shadow_tpu.host.engine_app import (EngineAppProcess,
                                                    engine_app_args)
            spec = engine_app_args(pcfg, h, h.dns)
            if spec is not None:
                kind, a, b, c, d, e = spec[:6]
                extra = spec[6:]  # e.g. the udp-mesh peer buffer
                sh = h.syscall_handler
                process = EngineAppProcess(
                    h, f"{pcfg.path}.{self.index}",
                    expected_final_state=pcfg.expected_final_state)
                process.spawn_tag = self.index
                process.app_idx = h.plane.engine.app_spawn(
                    h.id, kind, a, b, c, d, e, sh.send_buf,
                    sh.recv_buf, int(sh.send_autotune),
                    int(sh.recv_autotune), h.now(), *extra)
                return
        factory = app_registry.lookup(pcfg.path)
        if factory is None and "/" in pcfg.path:
            # An explicit filesystem path: a real Linux binary, run
            # under the interposition stack (preload shim + seccomp
            # over the shmem IPC channel; host/managed.py).  Bare
            # names never fall through to $PATH — a typo'd internal-
            # app name must not execute some unrelated host program.
            from shadow_tpu.host.managed import ManagedProcess
            base = os.path.basename(pcfg.path)
            process = ManagedProcess(
                h, f"{base}.{self.index}",
                [pcfg.path] + list(pcfg.args),
                pcfg.environment,
                expected_final_state=pcfg.expected_final_state,
                work_dir=h.data_path)
            process.strace_mode = strace_mode
            process.spawn_tag = self.index
            # Failure-containment policy (docs/ROBUSTNESS.md): the
            # pcfg rides along so a `restart` policy can re-run this
            # very SpawnTask at the failure instant.
            process.on_failure = pcfg.on_failure
            process.restart_budget = pcfg.restart_budget
            process._pcfg = pcfg
            process.start_native(h, pcfg.path)
            return
        if factory is None:
            process = Process(h, f"{pcfg.path}.{self.index}", pcfg.args,
                              pcfg.environment,
                              expected_final_state=pcfg.
                              expected_final_state)
            process.strace_mode = strace_mode
            process.spawn_tag = self.index
            process.stderr += (f"[shadow-tpu] unknown app "
                               f"{pcfg.path!r}\n").encode()
            process.exited = True
            process.exit_code = 127
            return
        process = Process(h, f"{pcfg.path}.{self.index}", pcfg.args,
                          pcfg.environment,
                          expected_final_state=pcfg.expected_final_state)
        process.strace_mode = strace_mode
        process.spawn_tag = self.index
        process.app_path = pcfg.path  # checkpoint replay rebuild key
        process.start(h, factory(process, pcfg.args))


class ShutdownTask:
    """Picklable shutdown-signal task: delivers the configured signal
    to every process its paired SpawnTask created (matched by
    spawn_tag — no shared closure list, so a pickled pending shutdown
    still finds processes restored from a snapshot)."""

    __slots__ = ("index", "signal")

    def __init__(self, index: int, signal: int):
        self.index = index
        self.signal = signal

    def __call__(self, h) -> None:
        for proc in list(h.processes.values()):
            if getattr(proc, "spawn_tag", None) == self.index \
                    and not proc.exited:
                proc.raise_signal(h, self.signal)


class Manager:
    def __init__(self, config: ConfigOptions):
        from shadow_tpu.utils import object_counter
        object_counter.reset()
        self.config = config
        graph = config.network.graph
        if graph.latency_ns is None:
            graph.compute_routing(config.network.use_shortest_path)
        self.graph = graph

        self.dns = Dns()
        self.syscall_handler = SyscallHandler(
            send_buf=config.experimental.socket_send_buffer,
            recv_buf=config.experimental.socket_recv_buffer,
            send_autotune=config.experimental.socket_send_autotune,
            recv_autotune=config.experimental.socket_recv_autotune)
        from shadow_tpu.host.syscalls_native import NativeSyscallHandler
        self.syscall_handler_native = NativeSyscallHandler(
            send_buf=config.experimental.socket_send_buffer,
            recv_buf=config.experimental.socket_recv_buffer,
            send_autotune=config.experimental.socket_send_autotune,
            recv_autotune=config.experimental.socket_recv_autotune)

        # Opt-in crypto no-op preload: built ONCE here (worker threads
        # spawning concurrently must not race make) and handed to
        # hosts as a path.
        crypto_noop_path = None
        if config.experimental.openssl_crypto_noop:
            from shadow_tpu.native import ensure_crypto_noop_built
            crypto_noop_path = ensure_crypto_noop_built()

        # Build hosts in sorted-name order: host ids — and with them every
        # RNG stream and ordering tiebreak — are config-deterministic.
        from shadow_tpu.net.graph import IpAssignment
        ipa = IpAssignment()
        self.hosts: list[Host] = []
        seed = config.general.seed
        for host_id, name in enumerate(sorted(config.hosts)):
            hcfg = config.hosts[name]
            node = graph.by_gml_id.get(hcfg.network_node_id)
            if node is None:
                raise ValueError(f"host {name!r}: unknown network_node_id "
                                 f"{hcfg.network_node_id}")
            ip = ipa.assign(node.index, hcfg.ip_addr)
            bw_down = hcfg.bandwidth_down_bits or node.bandwidth_down_bits
            bw_up = hcfg.bandwidth_up_bits or node.bandwidth_up_bits
            if not bw_down or not bw_up:
                raise ValueError(f"host {name!r}: no bandwidth configured "
                                 "(host or graph node must provide it)")
            host = Host(host_id, name, ip, node.index, seed, bw_down, bw_up,
                        qdisc=config.experimental.interface_qdisc)
            host.tcp_cc = hcfg.tcp_cc
            host.tcp_ecn = hcfg.tcp_ecn
            # DCTCP-K marking threshold (sim-global experimental knob;
            # the sweep subsystem's congestion axis).  Instance attrs
            # so the router's object-path marking law reads the
            # configured value; ckpt restore re-applies the RESUMED
            # config's values over the pickled ones.
            host.dctcp_k_pkts = config.experimental.dctcp_k_pkts
            host.dctcp_k_bytes = config.experimental.dctcp_k_bytes
            if config.experimental.host_cpu_threshold_ns is not None:
                from shadow_tpu.host.cpu import Cpu
                host.cpu = Cpu(
                    threshold=config.experimental.host_cpu_threshold_ns,
                    precision=config.experimental.host_cpu_precision_ns)
                host.cpu_event_cost_ns = \
                    config.experimental.host_cpu_event_cost_ns
            host.syscall_latency_ns = (
                config.experimental.unblocked_syscall_latency_ns
                if config.general.model_unblocked_syscall_latency else 0)
            if config.experimental.native_preemption_enabled:
                host.preempt_native_ns = \
                    config.experimental.native_preemption_native_interval_ns
                host.preempt_sim_ns = \
                    config.experimental.native_preemption_sim_interval_ns
            host.max_unapplied_ns = \
                config.experimental.max_unapplied_cpu_latency_ns
            # Waitpid safety-net poll slice for managed-thread IPC
            # recvs (was hard-coded; surfaced in
            # metrics.wall.ipc.death_poll_ns).
            host.death_poll_ns = \
                config.experimental.managed_death_poll_ns
            host.crypto_noop = crypto_noop_path  # lib path or None
            bw = config.experimental.native_file_io_bandwidth_bps
            if config.general.model_unblocked_syscall_latency and bw > 0:
                # ns per KiB at the modeled disk bandwidth.
                host.native_io_ns_per_kib = max(
                    1, (1_000_000_000 * 1024) // bw)
            host.dns = self.dns
            host.syscall_handler = self.syscall_handler
            host.syscall_handler_native = self.syscall_handler_native
            host.data_path = os.path.join(config.general.data_directory,
                                          "hosts", name)
            host.strace_mode = (
                None if config.experimental.strace_logging_mode == "off"
                else config.experimental.strace_logging_mode)
            # A configured `checkpoint:` block turns on syscall-
            # transcript recording (ckpt/replay.py): the object path's
            # generator frames resume through replay, so recording
            # must cover the whole run.
            host.ckpt_record = config.checkpoint is not None
            self.dns.register(host_id, ip, name)
            self.hosts.append(host)
            for i, pcfg in enumerate(hcfg.processes):
                self._schedule_spawn(host, i, pcfg)
        self._host_by_name = {h.name: h.id for h in self.hosts}
        # Fault-schedule cursor: how many `faults:` entries have been
        # applied (restored by ckpt resume so a resumed run re-applies
        # only the remainder).
        self._faults_applied = 0
        # tpu_shards > 1 fault refusal LIFTED (docs/ROBUSTNESS.md):
        # the mesh propagator's send carries the link_down egress twin,
        # arrivals drop at their path-independent instants via the
        # inbox-pop checks on every plane, and both device-span
        # kernels thread the per-host fault mask (h_fault) through
        # their 4-side-checked codecs.

        # Loss thresholds as an integer matrix: one float->int conversion
        # at build time, shared verbatim by scalar and batched backends.
        loss = graph.packet_loss
        thr = np.zeros(loss.shape, dtype=np.int64)
        nz = loss > 0
        if nz.any():
            thr[nz] = [loss_threshold_u32(p) for p in loss[nz]]
        self.loss_thresholds = thr

        self.runahead = Runahead(
            config.experimental.runahead_ns, graph.min_latency_ns(),
            config.experimental.use_dynamic_runahead)

        sched = config.experimental.scheduler
        threaded = sched in ("thread_per_core", "thread_per_host")
        self._per_host_tasks = sched == "thread_per_host"
        self._nt: list = []          # shared per-host next-event snapshot

        # ---- syscall service plane (shadow_tpu/svc/, docs/
        # OBSERVABILITY.md "Syscall service plane") ------------------
        # Managed (real-binary) hosts are known from config: a process
        # configured by filesystem path that no internal-app factory
        # claims runs under the interposition stack (SpawnTask's
        # dispatch rule).  They are flagged up front — svc_managed
        # routes their round servicing to the host-affine worker pool;
        # py_pinned keeps their py-work slot permanently True so the
        # engine's span loop stops before any window that would touch
        # one (the quiescence gate's safety argument, netplane.cpp
        # span_eligible).
        managed_hosts = []
        for host in self.hosts:
            hcfg = config.hosts[host.name]
            if any("/" in pcfg.path
                   and app_registry.lookup(pcfg.path) is None
                   for pcfg in hcfg.processes):
                host.svc_managed = True
                host.py_pinned = True
                managed_hosts.append(host)
            else:
                host.svc_managed = False
        # ---- failure containment plane (svc/containment.py,
        # docs/ROBUSTNESS.md) ----------------------------------------
        # Built whenever managed processes are configured: it owns the
        # hang watchdog, the per-process on_failure policies' pending
        # quarantines, and the fault ledger.  Resource preflight runs
        # first — a fleet that cannot fit the fd table or /dev/shm
        # must fail (or warn, under an all-quarantine fleet) before
        # the first spawn, naming the exact limit to raise.
        self.containment = None
        if managed_hosts:
            from shadow_tpu.svc.containment import (ContainmentPlane,
                                                    preflight_managed)
            # The ONE managed-process predicate is the SpawnTask
            # dispatch rule applied above to flag managed_hosts;
            # collect the matching pcfgs once so preflight sizing and
            # the warn-only gate cannot drift from what spawns.
            managed_pcfgs = [
                pcfg for host in managed_hosts
                for pcfg in config.hosts[host.name].processes
                if "/" in pcfg.path
                and app_registry.lookup(pcfg.path) is None]
            preflight_managed(
                len(managed_pcfgs),
                warn_only=all(p.on_failure == "quarantine"
                              for p in managed_pcfgs))
            self.containment = ContainmentPlane(
                watchdog_ns=config.experimental.managed_watchdog_ns)
            for host in managed_hosts:
                host.containment = self.containment
                host.spawn_stagger_ns = \
                    config.experimental.managed_spawn_stagger_ns
        svc_mode = config.experimental.syscall_service_plane
        # parallelism 0 = auto (num cores), matching the schedulers.
        svc_workers = config.general.parallelism or os.cpu_count() or 1
        svc_workers = max(1, int(svc_workers))
        svc_on = (bool(managed_hosts)
                  and not config.experimental.use_perf_timers
                  and (svc_mode == "on"
                       or (svc_mode == "auto" and svc_workers > 1)))
        self.svc = None
        if svc_on:
            from shadow_tpu.svc import SyscallServicePlane
            self.svc = SyscallServicePlane(
                max(1, min(svc_workers, len(managed_hosts))))
            for host in managed_hosts:
                # Advertised to the shim via the IPC v8 svc_flags
                # header word (spin-then-wait for responses).
                host.svc_active = True
        self._managed_mask = None  # built in _init_next_times

        # Native (C++) data plane: the performance path behind
        # scheduler=tpu.  Per-host opt-out keeps pcap capture and the
        # CPU model on the object path; both planes interop through the
        # propagator (cross-plane packet conversion).
        self.plane = None
        native_mode = config.experimental.native_dataplane
        # tpu: engine on by default (auto).  thread_per_core: engine on
        # explicit opt-in only (native_dataplane: on) — that mode is
        # the honest baseline comparator (real OS threads over C++
        # engine hosts, run_hosts_mt), and the default must stay the
        # reference-faithful pure-Python scheduler.
        want_plane = (sched == "tpu" and native_mode != "off") or \
            (sched == "thread_per_core" and native_mode == "on")
        if want_plane:
            from shadow_tpu.native import plane as native_plane
            if native_plane.native_available():
                self.plane = native_plane.NativePlane(self.hosts)
                qdisc_rr = config.experimental.interface_qdisc == \
                    "round_robin"
                for host in self.hosts:
                    if host.cpu is None and \
                            config.hosts[host.name].native_dataplane:
                        self.plane.add_host(host, qdisc_rr)
                # Engine-global DCTCP-K (CoDelN::push reads it): set
                # from config — never snapshotted, so a forked archive
                # resumes under the VARIANT's K (tools/ckpt fork).
                self.plane.engine.set_dctcp_k(
                    config.experimental.dctcp_k_pkts,
                    config.experimental.dctcp_k_bytes)
            elif native_mode == "on":
                raise RuntimeError(
                    f"native_dataplane=on but the engine is unavailable: "
                    f"{native_plane.load_error()}")

        # Pcap capture: engine hosts record in C++ (drained per round
        # into the same frame builder — files byte-identical to the
        # object path's); object-path hosts hook the Python ifaces.
        self._pcap_engine: list = []  # (host, writer_lo, writer_eth)
        for host in self.hosts:
            hcfg = config.hosts[host.name]
            if not hcfg.pcap_enabled:
                continue
            from shadow_tpu.utils.pcap import PcapWriter
            hdir = host.data_path
            os.makedirs(hdir, exist_ok=True)
            writers = tuple(
                PcapWriter(os.path.join(hdir, f"{name}.pcap"),
                           hcfg.pcap_capture_size)
                for name in ("lo", "eth0"))
            if host.plane is not None:
                for ifidx in (0, 1):
                    self.plane.engine.set_pcap(host.id, ifidx, True)
                self._pcap_engine.append((host,) + writers)
            else:
                host.lo.pcap, host.eth0.pcap = writers

        if sched == "tpu" and config.experimental.tpu_shards > 1:
            from shadow_tpu.parallel.mesh_propagator import MeshPropagator
            self.propagator = MeshPropagator(
                self.hosts, self.dns, graph.latency_ns, thr, seed,
                config.general.bootstrap_end_time_ns,
                n_shards=config.experimental.tpu_shards,
                exchange_capacity=config.experimental.tpu_exchange_capacity,
                max_batch=config.experimental.tpu_max_packets_per_round,
                min_device_batch=config.experimental.tpu_min_device_batch,
                runahead=self.runahead)
        elif sched == "tpu":
            from shadow_tpu.ops.propagate import TpuPropagator
            self.propagator = TpuPropagator(
                self.hosts, self.dns, graph.latency_ns, thr, seed,
                config.general.bootstrap_end_time_ns,
                max_batch=config.experimental.tpu_max_packets_per_round,
                min_device_batch=config.experimental.tpu_min_device_batch,
                runahead=self.runahead)
        else:
            # The service plane executes managed hosts concurrently
            # even under scheduler=serial, so the propagator's
            # min-inflight reduction must take its threaded (locked)
            # form whenever the plane is active.
            self.propagator = ScalarPropagator(
                self.hosts, self.dns, graph.latency_ns, thr, seed,
                config.general.bootstrap_end_time_ns,
                threaded=threaded or self.svc is not None,
                runahead=self.runahead)
        for host in self.hosts:
            host._send_packet_fn = self.propagator.send
        if self.plane is not None:
            # Register the propagation phase's routing state with the
            # engine: sends from native hosts batch engine-side and
            # finish_round runs the scalar twin (or the device kernel)
            # without per-packet Python.
            from shadow_tpu.core.rng import STREAM_PACKET_LOSS, mix_key
            from shadow_tpu.core.simtime import TIME_NEVER
            k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
            lat = np.ascontiguousarray(graph.latency_ns, dtype=np.int64)
            self.plane.engine.set_routing(
                np.ascontiguousarray(
                    [h.node_index for h in self.hosts], dtype=np.int32),
                np.ascontiguousarray([h.ip for h in self.hosts],
                                     dtype=np.uint32),
                lat, np.ascontiguousarray(thr, dtype=np.int64),
                lat.shape[0], k0, k1,
                config.general.bootstrap_end_time_ns, TIME_NEVER)
            self.propagator.engine = self.plane.engine

        # OS-thread width for the engine's run_hosts_mt parallel
        # sections (any scheduler with the plane active).
        self._mt_threads = (config.general.parallelism
                            or os.cpu_count() or 1)

        self._perf_timers = config.experimental.use_perf_timers
        if self._perf_timers and threaded:
            # Per-host timing is only meaningful serially (threads share
            # the GIL); don't build a pool that would sit idle.
            import sys as _sys
            print("[shadow-tpu] use_perf_timers forces serial host "
                  "execution; parallelism ignored", file=_sys.stderr)
            threaded = False
        if threaded:
            workers = config.general.parallelism or os.cpu_count() or 1
            n_workers = min(workers, len(self.hosts))
            initializer = None
            if config.experimental.use_cpu_pinning:
                initializer = _make_pinner()
            self._pool = ThreadPoolExecutor(max_workers=n_workers,
                                            initializer=initializer)
            import threading as _threading
            self._steal_lock = _threading.Lock()
        else:
            self._pool = None

        # Observability (shadow_tpu/trace/, docs/OBSERVABILITY.md).
        # The metrics registry and the device-eligibility audit are
        # ALWAYS on (integer adds per round/span — they feed
        # sim-stats.json's metrics block); the flight recorder's
        # channels are opt-in: "on" records the deterministic sim-time
        # event stream plus wall phases, "wall" phases only.
        from shadow_tpu.trace.audit import EligibilityAudit
        from shadow_tpu.trace.metrics import MetricsRegistry
        self.metrics = MetricsRegistry()
        self.audit = EligibilityAudit()
        self.flight = None
        fr_mode = config.experimental.flight_recorder
        if fr_mode in ("on", "wall"):
            from shadow_tpu.trace.recorder import FlightRecorder
            self.flight = FlightRecorder(sim=(fr_mode == "on"))
            if self.flight.sim is not None and self.plane is not None:
                # Engine-side fixed-record ring: per-round milestones
                # inside C++ spans, drained after each span.
                self.plane.engine.set_flight(1)
            # Wall-phase hook for the per-round dispatch path.
            self.propagator.wall = self.flight.wall
        # Sim-netstat (trace/netstat.py): the deterministic
        # per-connection TCP telemetry channel.  Drop-cause ATTRIBUTION
        # is always on (Host.trace_drop / the engine's trace_drop map
        # every drop to one TEL_* cause); the sample channel is opt-in.
        self.netstat = None
        if config.experimental.sim_netstat == "on":
            from shadow_tpu.trace.netstat import NetstatChannel
            self.netstat = NetstatChannel(
                config.experimental.netstat_interval_ns)
            if self.plane is not None:
                # Engine-side fixed-record telemetry ring: per-round
                # connection samples inside C++ spans and on the
                # per-round path, drained alongside the span exports.
                self.plane.engine.set_netstat(
                    1, max(int(config.experimental.netstat_interval_ns),
                           1))
        # Fabric observatory (trace/fabricstat.py): the deterministic
        # per-link queue telemetry + flow-completion-time channel.
        # The conservation COUNTERS (CoDel enqueue/forward/drop, relay
        # stalls, flow lifecycle) are always on — integer adds like
        # drop attribution; the sample channel is opt-in.
        self.fabric = None
        if config.experimental.sim_fabricstat == "on":
            from shadow_tpu.trace.fabricstat import FabricChannel
            self.fabric = FabricChannel(
                config.experimental.fabricstat_interval_ns)
            if self.plane is not None:
                # Engine-side fixed-record ring: per-round queue
                # samples inside C++ spans and on the per-round path,
                # drained alongside the span exports.
                self.plane.engine.set_fabric(
                    1,
                    max(int(config.experimental.fabricstat_interval_ns),
                        1))
        # Device-kernel observatory (trace/kernstat.py,
        # docs/OBSERVABILITY.md "Device-kernel observatory"): "on"
        # records the per-committed-span stage-counter channel
        # (kernel-sim.bin); "wall"/"on" enable the wall-side dispatch
        # attribution in the span runners (fn-cache accounting, AOT
        # cost_analysis, codec byte volume, rollback ledger).
        self.kern = None
        if config.experimental.kernel_observatory == "on":
            from shadow_tpu.trace.kernstat import KernChannel
            self.kern = KernChannel()
        # Syscall observatory (trace/sctrace.py, docs/OBSERVABILITY.md
        # "syscall observatory"): SC_* disposition counters are ALWAYS
        # on (Host.sc_disp integer adds, like drop attribution); the
        # wall-time IPC round-trip profile and the per-syscall
        # sim-time record channel are opt-in.
        self.sctrace = None
        if config.experimental.syscall_observatory in ("wall", "on"):
            from shadow_tpu.trace.sctrace import SyscallObservatory
            self.sctrace = SyscallObservatory(
                config.experimental.syscall_observatory, self.hosts,
                death_poll_ns=config.experimental.managed_death_poll_ns)

    # ------------------------------------------------------------------

    def _schedule_spawn(self, host: Host, index: int, pcfg) -> None:
        # SpawnTask/ShutdownTask are module-level picklable callables
        # (a checkpoint carries pending spawns inside the pickled
        # event queue; a closure over the Manager could not resume).
        from shadow_tpu.core.event import TaskRef
        host.schedule_task_at(pcfg.start_time_ns,
                              TaskRef("spawn", SpawnTask(pcfg, index)))
        if pcfg.shutdown_time_ns is not None:
            # Deliver the configured shutdown signal through the emulated
            # signal path (ref: configuration.rs host process spec) — a
            # managed process with a handler exits through it; default
            # disposition terminates.
            from shadow_tpu.host.signals import parse_signal
            shutdown_sig = parse_signal(pcfg.shutdown_signal or "SIGTERM")
            host.schedule_task_at(
                pcfg.shutdown_time_ns,
                TaskRef("shutdown", ShutdownTask(index, shutdown_sig)))

    # ------------------------------------------------------------------
    # The round loop (manager.rs:415-501)
    # ------------------------------------------------------------------

    def _init_next_times(self) -> None:
        """Build the shared next-event snapshot (one slot per host).
        After this, maintenance is incremental: each host writes its own
        slot at the end of execute(), and cross-host deliveries lower
        the destination slot under the inbox lock — the per-round
        barrier is one min() over a flat list instead of 2N queue peeks
        (the reference reduces per-thread minimums the same lazy way,
        manager.rs:447-487)."""
        from shadow_tpu.core.simtime import TIME_NEVER
        nt = np.empty(len(self.hosts), dtype=np.int64)
        for h in self.hosts:
            t = h.next_event_time()
            nt[h.id] = TIME_NEVER if t is None else t
        self._nt = nt
        # Python-work partition flags for the engine fast path: object-
        # path hosts are permanently True; plane hosts start from their
        # real heap/inbox state and maintain the slot incrementally
        # (schedule/deliver set it, execute-end recomputes it).
        pw = np.ones(len(self.hosts), dtype=bool)
        mng = np.zeros(len(self.hosts), dtype=bool)
        any_mng = False
        for h in self.hosts:
            h._nt_list = nt
            if h.plane is not None:
                h._py_work_arr = pw
                # py_pinned (managed hosts): the slot never recomputes
                # to False — the quiescence gate's safety net.
                pw[h.id] = bool(h.queue._heap) or bool(h._inbox) \
                    or h.py_pinned
            if getattr(h, "svc_managed", False):
                mng[h.id] = True
                any_mng = True
        self._py_work = pw
        self._managed_mask = mng if any_mng else None
        if self.plane is not None:
            self.plane.engine.set_nt(nt)
            # Span loop safety: the engine must know which hosts carry
            # Python-side work (their nt slots hold Python-heap times
            # the engine-side refresh would wipe).
            self.plane.engine.set_py_work(pw)

    def _min_next_event(self) -> int | None:
        from shadow_tpu.core.simtime import TIME_NEVER
        best = int(self._nt.min())
        return None if best >= TIME_NEVER else best

    def _object_block_reason(self, py_min: int) -> int:
        """Eligibility audit: classify WHY the earliest-due
        Python-side host keeps this round off the span path —
        permanent object-path hosts by cause (CPU model, pcap under
        per-host engine opt-out, other config), engine hosts carrying
        transient Python work (spawn/shutdown heap tasks) as py-task.
        `py_min` is the caller's already-computed minimum over the
        py-flagged slots, so this is one boolean scan on the (rare)
        blocked path, not a fresh int64 argmin."""
        from shadow_tpu.trace import events as trev
        idx = np.flatnonzero(self._py_work & (self._nt == py_min))
        h = self.hosts[int(idx[0])]
        if h.plane is not None:
            return trev.EL_OBJ_PYTASK
        if h.cpu is not None:
            return trev.EL_OBJ_CPU
        if self.config.hosts[h.name].pcap_enabled:
            return trev.EL_OBJ_PCAP
        return trev.EL_OBJ_OTHER

    def _active_hosts(self, until: int) -> list:
        """Hosts whose `execute(until)` would do work per the shared
        snapshot (which inbox deliveries and engine pushes keep
        current).  At scale most hosts are idle most rounds; skipping
        them is a pure win because the barrier already covers in-flight
        packets via the propagator's finish_round min.  With the
        syscall service plane active, managed hosts are excluded —
        they drain concurrently on the plane's worker pool."""
        hosts = self.hosts
        mask = self._nt < until
        if self.svc is not None and self._managed_mask is not None:
            mask &= ~self._managed_mask
        return [hosts[i] for i in np.flatnonzero(mask)]

    def _run_engine_batch(self, until: int, nthreads: int) -> list:
        """Engine fast path: hosts whose pending work is entirely
        engine-side (no Python heap entries, no undrained Python
        inbox — the maintained _py_work flags) run the whole window in
        ONE C call; callback-free hosts inside that call fan out over
        OS threads (run_hosts_mt, GIL released).  Returns the hosts
        that still need the Python path.  The partition is pure numpy:
        at 10k+ hosts a per-round Python probe of every active host
        was ~10% of the round loop."""
        eng = self.plane.engine
        mask = self._nt < until
        if self.svc is not None and self._managed_mask is not None:
            # Managed hosts drain on the service plane's worker pool.
            mask = mask & ~self._managed_mask
        fast = np.flatnonzero(mask & ~self._py_work)
        slow = np.flatnonzero(mask & self._py_work)
        if fast.size:
            stop = eng.run_hosts_mt(
                np.ascontiguousarray(fast, dtype=np.uint32), until,
                nthreads)
            if stop >= 0:
                # A Python callback fired in the serial tail: finish
                # that host and the remainder via the full merge loop
                # (already-run hosts re-execute as no-ops).
                for hid in fast[stop:].tolist():
                    self.hosts[hid].execute(until)
        hosts = self.hosts
        return [hosts[i] for i in slow.tolist()]

    def _drain_engine_pcap(self) -> None:
        eng = self.plane.engine
        for host, w_lo, w_eth in self._pcap_engine:
            for (ifidx, t, src, seq, proto, sip, sport, dip, dport,
                 payload, tcp) in eng.pcap_take(host.id):
                w = w_lo if ifidx == 0 else w_eth
                w.write_fields(t, src, seq, proto, sip, sport, dip,
                               dport, payload, tcp)

    def _run_hosts(self, until: int) -> None:
        svc_join = None
        if self.svc is not None and self._managed_mask is not None:
            # Syscall service plane: this round's due managed hosts
            # drain on the host-affine worker pool, OVERLAPPING the
            # scheduler's walk of everyone else below — the futex
            # waits of independent hosts' syscall round trips no
            # longer serialize.  Joined before returning, so the
            # propagation barrier still sees every send.
            due = np.flatnonzero((self._nt < until) & self._managed_mask)
            if due.size:
                svc_join = self.svc.dispatch(
                    [self.hosts[i] for i in due.tolist()], until)
        try:
            self._run_hosts_inner(until)
        finally:
            if svc_join is not None:
                svc_join()

    def _run_hosts_inner(self, until: int) -> None:
        if self._perf_timers:
            # perf_timers feature (perf_timer.rs; host.rs:680-688): time
            # each host's event execution.  Serial-only measurement keeps
            # the numbers meaningful (threads share the GIL).
            for h in self.hosts:
                t0 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] perf diagnostics only
                h.execute(until)
                h.perf_exec_ns += time.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] perf diagnostics only
            return
        if self._pool is None:
            if self.plane is not None:
                # At 100k hosts the per-host Python wrapper and the
                # C-call crossings are the round loop's main cost;
                # host-level OS-thread parallelism is orthogonal to
                # where the propagation phase runs.
                for h in self._run_engine_batch(until, self._mt_threads):
                    h.execute(until)
            else:
                for h in self._active_hosts(until):
                    h.execute(until)
            return
        if self._per_host_tasks:
            # thread_per_host (scheduler/thread_per_host.rs): one task per
            # host, pool-sized by min(cores, hosts).
            list(self._pool.map(lambda h: h.execute(until),
                                self._active_hosts(until)))
        else:
            if self.plane is not None:
                # Engine-backed thread_per_core: the honest reference-
                # style baseline the accelerator ratio is measured
                # against; leftovers run through the Python stealing
                # pool below.
                active = self._run_engine_batch(
                    until, self._pool._max_workers)
            else:
                active = self._active_hosts(until)
            if not active:
                return
            # thread_per_core (thread_per_core.rs:17-60): workers claim
            # blocks off one shared cursor, so a thread that drew cheap
            # hosts steals the remainder of an expensive neighbor's
            # share — the same load-balance property as the reference's
            # per-thread ArrayQueue stealing, in the shape the GIL
            # rewards (one atomic claim per block, not per task).
            # Python threads still serialize CPU work on the GIL, so
            # this validates the concurrency protocol more than it buys
            # speed — the TPU scheduler is the performance path.
            n = self._pool._max_workers
            block = max(1, len(active) // (n * 8))
            cursor = [0]
            lock = self._steal_lock

            def run_worker(_):
                while True:
                    with lock:
                        i = cursor[0]
                        cursor[0] = i + block
                    if i >= len(active):
                        return
                    for h in active[i:i + block]:
                        h.execute(until)

            list(self._pool.map(run_worker, range(n)))

    def run(self) -> SimSummary:
        import sys
        stop = self.config.general.stop_time_ns
        progress = self.config.general.progress
        heartbeat = self.config.general.heartbeat_interval_ns
        next_heartbeat = heartbeat
        wall_start = time.perf_counter()  # shadow-lint: allow[wall-clock] heartbeat/progress display
        status = None
        heartbeat_lines = progress
        from shadow_tpu.utils.shadow_log import LOG
        LOG.set_level(self.config.general.log_level)
        status_throttle = 0.2
        if progress:
            from shadow_tpu.utils.status_bar import StatusBar, make_status
            status = make_status(stop)
            # A \r-redrawing bar and newline heartbeats garble each other
            # on one TTY; the bar subsumes the heartbeat there.  On a
            # non-TTY every update is a permanent log line, so throttle
            # far harder (the heartbeat already covers cadence).
            heartbeat_lines = not isinstance(status, StatusBar)
            if heartbeat_lines:
                status_throttle = 1.0
        next_status_wall = 0.0
        summary = SimSummary()
        # A propagator with `provides_barrier` computes the global
        # min-next-event reduction itself (lax.pmin over the mesh in the
        # sharded backend) — the Python-side host scan is bypassed.
        device_barrier = getattr(self.propagator, "provides_barrier", False)
        self._init_next_times()
        start = self._min_next_event()
        if device_barrier:
            # The mesh backend folds local next-event times into its
            # pmin barrier: hand it the shared snapshot so its per-round
            # input is O(1) instead of an O(N) host scan, and the
            # idle-host filter composes (every delivery path — host
            # slot writes, inbox deliveries, engine pushes — maintains
            # the snapshot incrementally).
            self.propagator.set_nt(self._nt)
        # Multi-round spans (netplane.cpp run_span; SURVEY §7 hard part
        # (3)): behind scheduler=tpu, engine-pure stretches of the sim
        # iterate whole conservative windows inside one C call — the
        # host twin of the device-resident multi-round loop.  The
        # thread_per_core baseline keeps the reference's per-round
        # architecture (manager.rs:415-501).
        route = getattr(self.propagator, "route", None)
        # Spans serve the sharded mesh backend too (ISSUE 11: sharded
        # device spans are the default routed path for tpu_shards > 1
        # — the per-round mesh exchange covers only the residue), so
        # `device_barrier` no longer disables them.
        span_ok = (self.config.experimental.scheduler == "tpu"
                   and self.plane is not None
                   and not self._perf_timers
                   # Forced-device mode (min_device_batch<=0) is the
                   # parity/audit path: every round must go through the
                   # jitted kernel, so spans (whose propagation runs
                   # the C++ twin) stay out of the way.
                   and route is not None and route.min_device_batch > 0)
        # Device-resident multi-round spans (ops/phold_span.py): for
        # eligible sims whole windows step ON DEVICE; "auto" measures
        # device vs C++ span throughput per round and routes, "force"
        # always takes the device (parity gates), "off" disables.
        dev_mode = self.config.experimental.tpu_device_spans
        dev_span_on = span_ok and dev_mode in ("auto", "force", "on")
        # A caller may pre-seed a runner (e.g. the multichip dryrun
        # injects one with a device mesh attached) — keep it.  Two
        # device-span families: PHOLD/udp-mesh (ops/phold_span.py) and
        # the tgen steady-stream TCP family (ops/tcp_span.py); the
        # router tries phold first and falls through once it reports
        # the sim is not phold-shaped.
        self._dev_span = getattr(self, "_dev_span", None)
        self._dev_span_tcp = getattr(self, "_dev_span_tcp", None)
        dev_ns_round = None   # EWMA wall ns/round, device spans
        cpp_ns_round = None   # EWMA wall ns/round, C++ spans
        dev_probe_countdown = 0
        dev_aborts_row = 0
        deliver_exports = None  # lazy import (mixed-sim spans only)
        # Speculative multi-window sizing: how many conservative
        # windows one device dispatch may batch.  The kernel's
        # transactional abort marker is the rollback — an aborted
        # span costs one dispatch and imports nothing — so the router
        # can speculate: double the batch while spans run clean,
        # shrink hard on an abort.  Residency (ops/phold_span.py)
        # makes the re-dispatch after a short span nearly free, so
        # starting small costs little and caps the price of a wrong
        # runahead/domain prediction.  The start/floor/shrink
        # heuristics are config knobs (experimental.dev_span_k_*,
        # digest-skipped — wall-side routing only); the 2x growth cap
        # stays fixed.
        dev_span_K = self.config.experimental.dev_span_k_init
        dev_k_floor = self.config.experimental.dev_span_k_floor
        dev_k_shrink = self.config.experimental.dev_span_k_shrink
        # Overlapped span pipeline (ISSUE 16): when on, every device
        # span dispatch also carries the NEXT window's speculative
        # max-rounds (the post-commit doubling, computed up front so
        # the in-flight record's params match the next dispatch), and
        # the runner double-buffers asynchronously.
        overlap_on = self._span_overlap_on()
        from shadow_tpu.core.simtime import TIME_NEVER
        from shadow_tpu.trace import events as trev
        # Device-eligibility audit state: every conservative round is
        # credited EXACTLY ONE trev.EL_* reason code (account_span for
        # span-served rounds, the per-round tail for the rest), so the
        # attribution report always sums to summary.rounds.
        audit = self.audit
        flight = self.flight
        fr_sim = flight.sim if flight is not None else None
        fr_wall = flight.wall if flight is not None else None
        netstat = self.netstat
        fabric = self.fabric
        # Why the per-round path would run when spans are statically
        # unavailable (refined at runtime when span_ok drops).
        if self.config.experimental.scheduler != "tpu" \
                or self.plane is None or self._perf_timers:
            per_round_static = trev.EL_ROUND_SCHED
        elif route is None or route.min_device_batch <= 0:
            per_round_static = trev.EL_ROUND_FORCED
        else:
            per_round_static = trev.EL_ROUND_SCHED
        # Why device spans are off when they are (refined when the
        # router disables them at runtime).
        dev_off_reason = (trev.EL_ENGINE_OFF
                          if dev_mode not in ("auto", "force", "on")
                          else trev.EL_ENGINE_FAMILY)
        if dev_span_on and device_barrier \
                and len(self.hosts) % getattr(
                    self.propagator, "n_shards", 1) != 0:
            # Sharded placement law (ops/span_mesh.py): the host axis
            # must divide the mesh.  C++ spans still serve; the audit
            # names the shard-routing decision.
            dev_span_on = False
            dev_off_reason = trev.EL_ENGINE_UNSHARDED
        # -------- checkpoint/resume + fault injection ----------------
        # (shadow_tpu/ckpt/, docs/CHECKPOINT.md.)  Resume: seed the
        # round counters and the deterministic router ladder from the
        # snapshot, and cross-check the rebuilt state's next-event time
        # against the recorded boundary.  Boundary ops: one sorted list
        # of (time, kind, index) entries — faults before snapshots at
        # equal times, each applied at the FIRST round boundary at or
        # after its time through this single choke point.  Spans cap
        # their `limit` at the next op so no op ever lands mid-span.
        resume = getattr(self, "_resume", None)
        ckpts_done: list = []
        if resume is not None:
            summary.rounds = resume["rounds"]
            summary.span_rounds = resume["span_rounds"]
            summary.busy_end_ns = resume["busy_end_ns"]
            if start != resume["next_start_ns"]:
                from shadow_tpu.ckpt.format import CkptError
                raise CkptError(
                    f"resume integrity check failed: rebuilt next-event "
                    f"time {start} != snapshot boundary "
                    f"{resume['next_start_ns']}")
            live = resume.get("live", {})
            dev_span_K = int(live.get("dev_span_K", dev_span_K))
            dev_aborts_row = int(live.get("dev_aborts_row",
                                          dev_aborts_row))
            ckpts_done = list(live.get("ckpts_done", []))
        # Fault schedules KEEP device-resident spans (docs/
        # ROBUSTNESS.md): both SoA kernels carry the per-host fault
        # mask (h_fault, 4-side-checked through the span codecs) with
        # run_until-twin drop semantics, faults apply only at round
        # boundaries (which cap span `limit`), and set_host_fault
        # bumps state_epoch so resident state re-exports the flags.
        boundary_ops: list = []
        ck_cfg = self.config.checkpoint
        ck_dir = None
        if ck_cfg is not None:
            ck_dir = ck_cfg.directory or os.path.join(
                self.config.general.data_directory, "ckpt")
            for t in ck_cfg.at_ns:
                if t not in ckpts_done:
                    boundary_ops.append((t, 1, t))
        for fi in range(self._faults_applied, len(self.config.faults)):
            boundary_ops.append((self.config.faults[fi].at_ns, 0, fi))
        boundary_ops.sort()

        def apply_boundary_ops(at):
            """Apply every due op at this round boundary; returns the
            (possibly re-read) loop start."""
            nonlocal dev_span_K, dev_aborts_row
            while boundary_ops and at >= boundary_ops[0][0]:
                _t, kind, idx = boundary_ops.pop(0)
                if kind == 0:
                    self._apply_fault(self.config.faults[idx], at,
                                      fr_sim)
                    self._faults_applied = idx + 1
                    continue
                if getattr(self.propagator, "_outbox", None):
                    # Device per-round path mid-drain: defer the
                    # snapshot one boundary (the outbox empties next
                    # finish_round).
                    boundary_ops.insert(0, (at + 1, 1, idx))
                    boundary_ops.sort()
                    break
                from shadow_tpu.ckpt.snapshot import write_snapshot
                path = os.path.join(ck_dir, f"ckpt-{idx}.stck")
                ckpts_done.append(idx)
                t0 = time.perf_counter()  # shadow-lint: allow[wall-clock] snapshot-write wall telemetry (bench[resume-10k])
                write_snapshot(
                    self, summary, at, path,
                    live={"dev_span_K": dev_span_K,
                          "dev_aborts_row": dev_aborts_row,
                          "ckpts_done": list(ckpts_done)})
                self.ckpt_write_wall_s = time.perf_counter() - t0  # shadow-lint: allow[wall-clock] snapshot-write wall telemetry (bench[resume-10k])
                self.ckpt_last_path = path
                from shadow_tpu.utils.shadow_log import LOG
                LOG.info(f"checkpoint written: {path} (round "
                         f"{summary.rounds}, sim {at / 1e9:.6f}s, "
                         f"{self.ckpt_write_wall_s:.2f}s wall)")
            return at

        while start is not None and start < stop:
            if boundary_ops and start >= boundary_ops[0][0]:
                start = apply_boundary_ops(start)
            if self.containment is not None \
                    and self.containment.has_pending:
                # Containment quarantines apply at the SAME choke
                # point as scheduled faults — the round boundary —
                # after any due scheduled ops, so a ledger replay's
                # `faults:` quarantine (applied above) dedups the
                # containment trigger and the flight bytes agree
                # (docs/ROBUSTNESS.md).
                for hid, _cause in self.containment.take_pending():
                    self._apply_quarantine(hid, start, fr_sim)
            round_reason = per_round_static
            if span_ok:
                if getattr(self.propagator, "_outbox", None):
                    span_now = False
                    round_reason = trev.EL_ROUND_OUTBOX
                elif not self.propagator.span_gate():
                    span_now = False
                    round_reason = trev.EL_ROUND_GATE
                else:
                    span_now = True
            else:
                span_now = False
            py_limit = None
            py_quiescent = False
            if span_now and self._py_work.any():
                # Python-side work pending somewhere — transient heap
                # tasks (spawns/shutdowns) on engine hosts, or
                # PERMANENT object-path hosts (pcap/strace/CPU-model)
                # in a mixed sim.  Either way spans may still serve
                # the stretch UP TO the earliest window that could
                # touch one: a window [s, s+ra) with s <= py_min - ra
                # keeps window_end <= py_min, so the Python event can
                # never fall inside a C++-served window (dynamic
                # runahead only shrinks).  An object-path host can
                # also RECEIVE from engine hosts mid-span; the engine
                # then ENDS the span at the producing round and hands
                # the exports back (run_span span-exports), delivered
                # below — event order stays identical to per-round.
                py_min = int(self._nt[self._py_work].min())
                ra = self.runahead.get()
                if start > py_min - ra:
                    span_now = False
                    # A Python-side host is due this round: attribute
                    # it (pcap / cpu-model / transient py-task / ...).
                    round_reason = self._object_block_reason(py_min)
                else:
                    py_limit = py_min - ra + 1
                    # Quiescence gate (syscall service plane): when
                    # the EARLIEST Python-side work belongs entirely
                    # to managed hosts — every managed process parked
                    # on a condition with no expiry before py_min —
                    # the span rounds below are managed-quiescent
                    # coverage, attributed under their own EL_* code.
                    if self._managed_mask is not None:
                        idx = np.flatnonzero(self._py_work
                                             & (self._nt == py_min))
                        py_quiescent = bool(idx.size) and bool(
                            self._managed_mask[idx].all())
            if span_now:
                limit = stop
                if heartbeat_lines:
                    limit = min(limit, next_heartbeat)
                if py_limit is not None:
                    limit = min(limit, py_limit)
                if boundary_ops:
                    # Checkpoint/fault ops apply at round boundaries
                    # only: cap the span so the loop regains control
                    # at (or before) the next op's time.  `limit`
                    # never changes window sequencing, so traces are
                    # unaffected.
                    limit = min(limit, boundary_ops[0][0])
                # With engine-side pcap, cap the span so capture
                # buffers hold at most pcap_span_cap rounds of packets
                # before the drain below (per-round streams; spans
                # must not buffer a whole sim).
                max_rounds = (
                    self.config.experimental.pcap_span_cap
                    if self._pcap_engine else 1024)

                def account_span(res, reason, device=False,
                                 family=trev.FAM_CPP):
                    """Book one completed span (C++ or device) and
                    advance the loop.  Returns the next window start
                    (None = simulation drained)."""
                    rounds, busy_rounds, pkts, next_start, busy_end, \
                        ra = res
                    base_round = summary.rounds
                    summary.rounds += rounds
                    summary.span_rounds += rounds
                    summary.busy_end_ns = busy_end
                    audit.add(reason, rounds)
                    if fr_sim is not None:
                        fr_sim.event(start, trev.FR_SPAN_START, family,
                                     0, base_round)
                        if not device:
                            # Engine per-round records (window_end,
                            # packets, window start) drained through
                            # the span-export path; re-stamped with
                            # the refined eligibility reason.
                            fr_sim.extend_engine(
                                *self.plane.engine.flight_take(),
                                reason=reason)
                        fr_sim.event(busy_end, trev.FR_SPAN_COMMIT,
                                     family, pkts, rounds)
                    if netstat is not None and not device:
                        # Per-connection samples the C++ span recorded
                        # at its round boundaries (device spans append
                        # theirs in the runner, at span commit).
                        netstat.extend(
                            *self.plane.engine.netstat_take())
                    if fabric is not None and not device:
                        # Per-queue samples, same drain discipline.
                        fabric.extend(
                            *self.plane.engine.fabric_take())
                    self.runahead.sync_from_span(ra)
                    prop = self.propagator
                    # Audit split counts dispatches the way the
                    # per-round path does: only rounds that propagated
                    # packets.  Rounds stepped INSIDE a device span
                    # credit the device side of the split.
                    prop.rounds_dispatched += busy_rounds
                    prop.packets_batched += pkts
                    if device:
                        prop.rounds_device = getattr(
                            prop, "rounds_device", 0) + busy_rounds
                        prop.packets_device = getattr(
                            prop, "packets_device", 0) + pkts
                    if self._pcap_engine:
                        self._drain_engine_pcap()
                    nonlocal next_heartbeat, next_status_wall
                    if heartbeat_lines and busy_end >= next_heartbeat:
                        self._log_heartbeat(busy_end, stop, wall_start,
                                            sys.stderr)
                        next_heartbeat = busy_end + heartbeat
                    if status is not None:
                        wall = time.perf_counter()  # shadow-lint: allow[wall-clock] status-bar redraw throttle
                        if wall >= next_status_wall:
                            status.update(busy_end)
                            next_status_wall = wall + status_throttle
                    return (None if next_start >= TIME_NEVER
                            else next_start)

                # ---- device-resident span (ops/phold_span.py) ----
                # Only in the fully-pure case: span_import_phold
                # recomputes every nt slot from engine state, which
                # would wipe a py-flagged host's Python-heap time (the
                # C++ span protects those via the shared pw flags; the
                # device import cannot).
                use_dev = False
                # Reason the rounds below land in a C++ span instead
                # of a device span (the audit's engine-span:* split).
                if py_limit is not None:
                    span_reason = (trev.EL_SVC_QUIESCENT if py_quiescent
                                   else trev.EL_ENGINE_PYLIMIT)
                elif not dev_span_on:
                    span_reason = dev_off_reason
                else:
                    span_reason = trev.EL_ENGINE_COLD
                if dev_span_on and py_limit is None:
                    if dev_mode in ("force", "on"):
                        use_dev = True
                    elif dev_ns_round is not None \
                            and cpp_ns_round is not None:
                        use_dev = dev_ns_round < cpp_ns_round
                        span_reason = trev.EL_ENGINE_ROUTED
                    elif dev_ns_round is None:
                        # Unmeasured: probing pays the device loop's
                        # XLA compile (tens of seconds on a slow
                        # backend), so only long runs earn it — the
                        # same 1%-of-wall budget the route model uses.
                        elapsed = time.perf_counter() - wall_start  # shadow-lint: allow[wall-clock] device-probe budget; both routes byte-identical
                        use_dev = (dev_probe_countdown <= 0
                                   and elapsed * 0.01 >= 5.0)
                dev_retry_soon = False
                if use_dev:
                    t0 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
                    res, runner = self._device_span(
                        start, stop, limit,
                        min(max_rounds, dev_span_K),
                        spec_mr=(min(dev_span_K * 2, max_rounds)
                                 if overlap_on else 0))
                    family = (trev.FAM_TCP
                              if runner is self._dev_span_tcp
                              else trev.FAM_PHOLD)
                    if res is not None and res[0] == 0:
                        # Zero progress (e.g. heartbeat boundary due
                        # now): benign — the C++/per-round path below
                        # handles the boundary.  Not a failure.
                        res = ZERO_PROGRESS
                    if res is not None and res is not ZERO_PROGRESS:
                        dev_aborts_row = 0
                        dev_span_K = min(dev_span_K * 2, max_rounds)
                        if runner.last_was_cold:
                            # Compile-tainted wall: discard the sample
                            # and re-measure warm on the next attempt.
                            dev_probe_countdown = 0
                        else:
                            dt = time.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
                            per = dt / max(res[0], 1)
                            dev_ns_round = per if dev_ns_round is None \
                                else 0.7 * dev_ns_round + 0.3 * per
                            dev_probe_countdown = 16
                        start = account_span(
                            res,
                            trev.EL_DEVICE_SHARDED
                            if getattr(runner, "mesh", None) is not None
                            else trev.EL_DEVICE_SPAN,
                            device=True, family=family)
                        continue
                    if res is None and (runner is None
                                        or runner.ineligible):
                        dev_span_on = False  # no device-span family fits
                        dev_off_reason = trev.EL_ENGINE_FAMILY
                        span_reason = trev.EL_ENGINE_FAMILY
                    elif res is None and getattr(runner,
                                                 "last_transient",
                                                 False):
                        # The TCP family's domain is state-dependent
                        # (handshake/close stretches fall outside it):
                        # not an abort — cap the C++ span below so the
                        # device is re-probed within a few windows
                        # instead of once per sim.
                        dev_retry_soon = True
                        span_reason = trev.EL_ENGINE_TRANSIENT
                    elif res is None:
                        # abort or transient over-caps: the rollback
                        # path — shrink the speculative window batch,
                        # back off, and give up only after repeated
                        # failures.  An exchange-capacity abort (the
                        # sharded hop kept overflowing after the
                        # driver's in-place growth) is attributed
                        # separately: it names a shard-routing limit,
                        # not a domain departure.
                        from shadow_tpu.ops.phold_span import AB_EXCH
                        span_reason = (
                            trev.EL_ENGINE_EXCHANGE
                            if getattr(runner, "last_abort_code", 0)
                            & AB_EXCH else trev.EL_ENGINE_ABORT)
                        if fr_sim is not None:
                            fr_sim.event(
                                start, trev.FR_SPAN_ABORT, family,
                                getattr(runner, "last_abort_code", 0),
                                0)
                        dev_span_K = max(dev_k_floor,
                                         dev_span_K // dev_k_shrink)
                        dev_aborts_row += 1
                        dev_probe_countdown = 16 * dev_aborts_row
                        if dev_aborts_row >= 3:
                            dev_span_on = False
                            dev_off_reason = trev.EL_ENGINE_ABORT
                elif dev_span_on:
                    dev_probe_countdown -= 1

                t0 = time.perf_counter_ns()  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
                res = self.plane.engine.run_span(
                    start, stop, limit, self.runahead.get(),
                    int(self.runahead.dynamic),
                    min(max_rounds, 16) if dev_retry_soon
                    else max_rounds,
                    self._mt_threads)
                if res is None:
                    span_ok = False  # callback-capable host: per-round
                    per_round_static = trev.EL_ROUND_CALLBACK
                    round_reason = per_round_static
                else:
                    exports = res[6]
                    res = res[:6]
                    if exports:
                        # Mixed sim: the span stopped at the round
                        # that addressed an object-path host; deliver
                        # those packets Python-side at their recorded
                        # times (>= that round's window_end).
                        if deliver_exports is None:
                            from shadow_tpu.ops.propagate import \
                                deliver_engine_exports as deliver_exports
                        deliver_exports(self.hosts, exports)
                    rounds = res[0]
                    if rounds:
                        dt = time.perf_counter_ns() - t0  # shadow-lint: allow[wall-clock] route pacing; both routes byte-identical
                        per = dt / rounds
                        cpp_ns_round = per if cpp_ns_round is None \
                            else 0.7 * cpp_ns_round + 0.3 * per
                        if fr_wall is not None:
                            fr_wall.add("engine-span", dt, t0)
                        start = account_span(res, span_reason)
                        if exports:
                            # the deliveries lowered object-host slots
                            nxt = self._min_next_event()
                            if nxt is not None and (start is None
                                                    or nxt < start):
                                start = nxt
                        continue
                    # rounds == 0 (e.g. heartbeat boundary due now):
                    # fall through to one per-round iteration.
                    round_reason = trev.EL_ROUND_BOUNDARY
            window_end = min(start + self.runahead.get(), stop)
            self.propagator.begin_round(start, window_end)
            if flight is not None:
                pk0 = getattr(self.propagator, "packets_batched", 0)
                t0 = fr_wall.now()
                self._run_hosts(window_end)
                t1 = fr_wall.now()
                fr_wall.add("host-loop", t1 - t0, t0)
                if self.sctrace is not None:
                    # Per-round managed-host phase wall: the slice of
                    # host-loop this round spent in the syscall seam
                    # (IPC wait + dispatch + resume), as its own
                    # flight-recorder phase.
                    d = self.sctrace.round_phase_delta()
                    if d:
                        fr_wall.add("syscall-service", d)
                inflight_min = self.propagator.finish_round()
                t2 = fr_wall.now()
                fr_wall.add("propagate", t2 - t1, t1)
                if fr_sim is not None:
                    fr_sim.event(
                        window_end, trev.FR_ROUND, round_reason,
                        getattr(self.propagator, "packets_batched",
                                0) - pk0, start)
            else:
                self._run_hosts(window_end)
                inflight_min = self.propagator.finish_round()
            if netstat is not None and netstat.sampled(start,
                                                       window_end):
                # Sim-netstat at the round boundary: engine-plane
                # connections sample through the C++ ring (canonical
                # host/port order); object-plane connections sample
                # here.  Homogeneous sims — what the cross-path
                # parity gates compare — emit one globally
                # host-sorted block per round either way.
                if self.plane is not None:
                    eng = self.plane.engine
                    eng.netstat_sample(start, window_end)
                    netstat.extend(*eng.netstat_take())
                netstat.sample_object_hosts(self.hosts, window_end)
            if fabric is not None and fabric.sampled(start,
                                                     window_end):
                # Fabric observatory at the same boundary, same
                # engine-block-then-object-block discipline (both in
                # ascending host-id order).
                if self.plane is not None:
                    eng = self.plane.engine
                    eng.fabric_sample(start, window_end)
                    fabric.extend(*eng.fabric_take())
                fabric.sample_object_hosts(self.hosts, window_end)
            audit.add(round_reason, 1)
            if self._pcap_engine:
                self._drain_engine_pcap()  # stream, don't buffer a sim
            summary.rounds += 1
            summary.busy_end_ns = window_end
            if heartbeat_lines and window_end >= next_heartbeat:
                self._log_heartbeat(window_end, stop, wall_start, sys.stderr)
                next_heartbeat = window_end + heartbeat
            if status is not None:
                wall = time.perf_counter()  # shadow-lint: allow[wall-clock] status-bar redraw throttle
                if wall >= next_status_wall:  # throttle redraws
                    status.update(window_end)
                    next_status_wall = wall + status_throttle
            if device_barrier:
                # finish_round already reduced host next-event times and
                # in-flight deliveries globally (pmin).
                start = inflight_min
            else:
                nxt = self._min_next_event()
                if inflight_min is not None and (nxt is None
                                                 or inflight_min < nxt):
                    nxt = inflight_min
                start = nxt
        summary.end_time_ns = min(start, stop) if start is not None else stop
        if self.containment is not None:
            # The round loop is over: end-of-run forced teardown of
            # still-running binaries must not read as failures, and a
            # quarantine still pending here has no round boundary left
            # to land on (its process is already marked contained).
            self.containment.active = False
        if status is not None:
            status.finish(summary.end_time_ns)

        # Final accounting (manager.rs:546-569).
        for h in self.hosts:
            h.merge_native_counters()
            summary.events += h.counters["events"]
            summary.packets_sent += h.counters["packets_sent"]
            summary.packets_recv += h.counters["packets_recv"]
            summary.packets_dropped += h.counters["packets_dropped"]
            summary.syscalls += h.counters["syscalls"]
            if h.down:
                # A killed host's processes died with it: their
                # expected_final_state is unjudgeable (the fault is
                # the configured outcome, not a plugin error).
                continue
            for proc in h.processes.values():
                if getattr(proc, "contained", None):
                    # The failure was contained (quarantine applied /
                    # restart consumed it) — the fault ledger is the
                    # record, not a plugin error (docs/ROBUSTNESS.md).
                    continue
                if not proc.matches_expected_final_state():
                    state = (f"exited {proc.exit_code}" if proc.exited
                             else "running")
                    summary.plugin_errors.append(
                        f"{h.name}/{proc.name}: expected "
                        f"{proc.expected_final_state!r}, got {state!r}")
        if self._pool is not None:
            self._pool.shutdown()
        if self.svc is not None:
            self.svc.shutdown()
        closer = getattr(self.propagator, "close", None)
        if closer is not None:
            closer()  # stop async route probes; never blocks
        # Teardown happens at one canonical instant — the simulation
        # end — on every host and plane: the closes below emit packets
        # (FINs of mid-stream connections), and per-host "last event"
        # clocks are scheduler-dependent state that must not leak into
        # the trace.
        for h in self.hosts:
            if h._now < summary.end_time_ns:
                h._now = summary.end_time_ns
        if self.plane is not None:
            self.plane.engine.advance_clocks(summary.end_time_ns)
        # Tear down any still-running managed (native) processes; flush
        # streamed strace files for processes that never exited.
        from shadow_tpu.host.managed import ManagedProcess
        for h in self.hosts:
            for proc in h.processes.values():
                if isinstance(proc, ManagedProcess) and not proc.exited:
                    proc.kill_native()
                    proc.collect_output()
                if not proc.exited:
                    # Forced teardown releases the fd table too, so the
                    # object-lifecycle accounting distinguishes real fd
                    # leaks from a server simply still running at
                    # stop_time.
                    proc.fds.close_all(h)
                    plow = getattr(proc, "fds_low", None)
                    if plow is not None:
                        plow.close_all(h)
                proc.strace_close()
        # Flush captures even when the caller never writes a data dir
        # (skip hosts whose lazy net plane never built — engine hosts
        # have no Python ifaces, and touching them here would build
        # 100k of them just to find no pcap).
        for h in self.hosts:
            if not h.net_built():
                continue
            for iface in (h.lo, h.eth0):
                if iface.pcap is not None:
                    iface.pcap.close()
        if self._pcap_engine:
            self._drain_engine_pcap()
            for _h, w_lo, w_eth in self._pcap_engine:
                w_lo.close()
                w_eth.close()
        return summary

    def drop_cause_totals(self) -> dict:
        """Packet-drop attribution summed over hosts: cause-name ->
        count (nonzero causes only; `unattributed` = drops whose
        reason has no TEL_* mapping — the conservation gate rejects
        any).  Engine counters merge through the hosts' incremental
        delta discipline, so this is safe mid-run and at the end."""
        from shadow_tpu.trace.events import TEL_N, TEL_NAMES
        causes = [0] * TEL_N
        unattributed = 0
        for h in self.hosts:
            h.merge_native_counters()
            for i in range(TEL_N):
                causes[i] += h.drop_causes[i]
            unattributed += h.drop_unattributed
        out = {TEL_NAMES[i]: causes[i] for i in range(TEL_N)
               if causes[i]}
        if unattributed:
            out["unattributed"] = unattributed
        return out

    def netstat_summary(self) -> dict:
        """bench.py's `drops` block: per-cause drop counts plus TCP
        stream totals (segments / retransmits) for the retransmit-rate
        figure.  Wall-side reporting only — never byte-diffed."""
        out = {"drops": self.drop_cause_totals()}
        if self.plane is not None:
            out["tcp"] = self.plane.engine.netstat_totals()
        else:
            totals = {"conns": 0, "segments_sent": 0,
                      "segments_received": 0, "retransmits": 0,
                      "sacked_skips": 0, "reasm_discards": 0,
                      "rcvwin_trunc": 0}
            from shadow_tpu.trace.netstat import iter_host_tcp_sockets
            for h in self.hosts:
                if not h.net_built():
                    continue
                for s in iter_host_tcp_sockets(h):
                    conn = s.conn
                    if conn is None:
                        continue
                    totals["conns"] += 1
                    totals["segments_sent"] += conn.segments_sent
                    totals["segments_received"] += \
                        conn.segments_received
                    totals["retransmits"] += conn.retransmit_count
                    totals["sacked_skips"] += conn.sacked_skip_count
                    totals["reasm_discards"] += conn.reasm_discards
                    totals["rcvwin_trunc"] += conn.rcvwin_trunc
            out["tcp"] = totals
        return out

    def _fabric_host_counters(self, h) -> tuple | None:
        """One host's fabric counter tuple (trace/fabricstat.py
        host_fabric_counters field order), from whichever path owns
        its queues; None when the host never built a net plane."""
        if h.plane is not None:
            return self.plane.engine.fabric_counters(h.id)
        if not h.net_built():
            return None
        from shadow_tpu.trace.fabricstat import host_fabric_counters
        return host_fabric_counters(h)

    def _fabric_sweep(self) -> tuple:
        """ONE walk over every host's fabric counters: the
        conservation ledger plus the hottest link's bits-sent/bw_up
        ratio (link-seconds of uplink traffic — fabric_summary
        divides by the sim duration for the utilization fraction).
        For every host: CoDel packets/bytes enqueued must equal
        forwarded + dropped + still-queued + relay-parked, and the
        drop count must reconcile against the TEL_CODEL +
        TEL_RTR_LIMIT attribution causes."""
        from shadow_tpu.trace.events import (MARK_N, MARK_NAMES,
                                             TEL_CODEL, TEL_RTR_LIMIT)
        totals = {"enqueued_pkts": 0, "enqueued_bytes": 0,
                  "delivered_pkts": 0, "delivered_bytes": 0,
                  "dropped_pkts": 0, "dropped_bytes": 0,
                  "marked_pkts": 0, "queued_pkts": 0,
                  "queued_bytes": 0, "peak_queue_depth": 0,
                  "refill_stalls": 0, "violations": 0}
        mark_causes = [0] * MARK_N
        max_link_s = 0.0
        for h in self.hosts:
            c = self._fabric_host_counters(h)
            if c is None:
                continue
            (enq_p, enq_b, fwd_p, fwd_b, drop_p, drop_b, marked,
             depth, qbytes, peak, r1s, r2s, _ps, bsent, _pr, _br,
             park_p, park_b) = c
            h.merge_native_counters()
            totals["enqueued_pkts"] += enq_p
            totals["enqueued_bytes"] += enq_b
            totals["delivered_pkts"] += fwd_p
            totals["delivered_bytes"] += fwd_b
            totals["dropped_pkts"] += drop_p
            totals["dropped_bytes"] += drop_b
            totals["marked_pkts"] += marked
            # a relay-parked packet is still inside the fabric:
            # report it on the queued side of the ledger
            totals["queued_pkts"] += depth + park_p
            totals["queued_bytes"] += qbytes + park_b
            totals["refill_stalls"] += r1s + r2s
            totals["peak_queue_depth"] = max(
                totals["peak_queue_depth"], peak)
            for i in range(MARK_N):
                mark_causes[i] += h.mark_causes[i]
            if h.bw_up_bits:
                max_link_s = max(max_link_s,
                                 bsent * 8 / h.bw_up_bits)
            attributed = (h.drop_causes[TEL_CODEL]
                          + h.drop_causes[TEL_RTR_LIMIT])
            # A marked packet is forwarded-with-mark: it stays on the
            # delivered/queued side, NEVER the dropped side — so the
            # byte identity is untouched by marking, and the marks
            # themselves must reconcile against the MARK_* attribution
            # (one cause per CE rewrite) and fit inside the accepted
            # population (each accepted packet marks at most once; a
            # marked packet may STILL be sojourn-dropped later by the
            # CoDel control law, so marks are bounded by enqueued —
            # not by enqueued minus dropped).
            marks_attributed = sum(h.mark_causes)
            if enq_p != fwd_p + drop_p + depth + park_p \
                    or enq_b != fwd_b + drop_b + qbytes + park_b \
                    or drop_p != attributed \
                    or marked != marks_attributed \
                    or marked > enq_p:
                totals["violations"] += 1
        totals["marks"] = {MARK_NAMES[i]: mark_causes[i]
                          for i in range(MARK_N) if mark_causes[i]}
        return totals, max_link_s

    def fabric_conservation(self) -> dict:
        """The conservation ledger (always available — the counters
        are on regardless of experimental.sim_fabricstat); the det
        gate and the incast smoke reject violations != 0."""
        return self._fabric_sweep()[0]

    def collect_fct_rows(self) -> list:
        """Every flow-lifecycle row in the sim: the per-host teardown
        logs plus the still-associated sweep, from both planes.  The
        caller (FabricChannel.write / the fct table) sorts."""
        rows: list = []
        if self.plane is not None:
            rows.extend(tuple(r) for r in self.plane.engine.fct_flows())
        from shadow_tpu.trace.fabricstat import object_host_flow_rows
        for h in self.hosts:
            if h.plane is None and h.net_built():
                rows.extend(object_host_flow_rows(h))
        return rows

    def fabric_summary(self, end_time_ns: int) -> dict:
        """bench.py's `fabric` block: conservation totals + peak queue
        depth, the hottest link's utilization fraction, and FCT
        percentiles where TCP flows exist.  Wall-side reporting only —
        the deterministic counters it renders live in
        metrics.sim.fabric."""
        cons, max_link_s = self._fabric_sweep()
        dur_s = end_time_ns / 1e9
        util = max_link_s / dur_s if dur_s > 0 else 0.0
        out = {
            "peak_queue_depth": cons["peak_queue_depth"],
            "refill_stalls": cons["refill_stalls"],
            "marked_pkts": cons["marked_pkts"],
            "marks": cons["marks"],
            "link_utilization": round(util, 4),
            "conservation": ("ok" if cons["violations"] == 0
                             else f"{cons['violations']} violations"),
        }
        # One aggregate FCT row over every flow (bench headline);
        # per-class detail stays in `trace fct`.  receiver_rows is the
        # shared de-dup rule: one record per flow, receiver vantage.
        from shadow_tpu.trace.fabricstat import (percentile,
                                                 receiver_rows)
        durs = sorted(r[1] - r[0]
                      for r in receiver_rows(self.collect_fct_rows()))
        if durs:
            out["fct"] = {
                "flows": len(durs),
                "p50_ns": percentile(durs, 500),
                "p99_ns": percentile(durs, 990),
                "p999_ns": percentile(durs, 999),
            }
        return out

    def sc_disposition_totals(self) -> dict:
        """Syscall-observatory dispositions summed over hosts:
        SC name -> count (nonzero only).  Always available — the
        counters are on regardless of experimental.syscall_observatory
        — and deterministic (they count Python-dispatched syscalls,
        which the cross-scheduler parity contract pins; engine-resident
        apps dispatch C++-side and sit outside this accounting)."""
        from shadow_tpu.trace.events import SC_N, SC_NAMES
        totals = [0] * SC_N
        for h in self.hosts:
            for i in range(SC_N):
                totals[i] += h.sc_disp[i]
        return {SC_NAMES[i]: totals[i] for i in range(SC_N)
                if totals[i]}

    def _make_span_runner(self, cls):
        """Shared device-span runner construction (the ONE place the
        arguments are derived, for every family — the multichip dryrun
        reuses these factories and attaches a device mesh)."""
        tracing = any(h.tracing_enabled for h in self.hosts)
        runner = cls(
            self.plane.engine, self.graph.latency_ns,
            self.loss_thresholds,
            np.ascontiguousarray(
                [h.node_index for h in self.hosts], dtype=np.int32),
            np.ascontiguousarray([h.ip for h in self.hosts],
                                 dtype=np.uint32),
            self.config.general.seed,
            self.config.general.bootstrap_end_time_ns, tracing)
        # Carry donation (experimental.tpu_donate_buffers): re-landed
        # behind the compile-cache-safe guard in ops/span_mesh.py
        # (BASELINE.md r6 documents the corrupting combination).
        runner.donate = \
            self.config.experimental.tpu_donate_buffers == "on"
        # DCTCP-K marking threshold: compile-time closure constants of
        # the jitted kernels (config-constant per Manager; part of the
        # kernel cache key).
        runner.dctcp_k = (self.config.experimental.dctcp_k_pkts,
                          self.config.experimental.dctcp_k_bytes)
        # Sharded device spans (ISSUE 11): under tpu_shards > 1 the
        # runners inherit the mesh propagator's device mesh, so whole
        # conservative windows iterate on device with the host axis
        # sharded and the cross-shard exchange inside the while_loop
        # — the default routed path, not a dryrun-only seam.  The
        # placement law requires H % shards == 0 (the router
        # attributes EL_ENGINE_UNSHARDED otherwise and never builds a
        # mesh-less sharded kernel).
        mesh = getattr(self.propagator, "mesh", None)
        if mesh is not None \
                and len(self.hosts) % mesh.devices.size == 0:
            runner.mesh = mesh
            runner.exchange_cap = \
                self.config.experimental.tpu_exchange_capacity
        if self.flight is not None:
            runner.wall = self.flight.wall  # dispatch phase profiling
        if self.netstat is not None:
            # Device spans buffer per-round connection samples in the
            # kernel and append them at span commit (tcp_span only;
            # the phold family has no TCP connections to sample).
            runner.netstat = self.netstat
        if self.fabric is not None:
            # Both families buffer per-round queue samples in the
            # kernel and append them at span commit.
            runner.fabric = self.fabric
        if self.kern is not None:
            # Both families thread per-stage fire/lane counters
            # through the while_loop carry and record one KS_REC per
            # committed span.
            runner.kern = self.kern
        if self.config.experimental.kernel_observatory in ("wall",
                                                           "on"):
            runner.kern_wall = True
        # Overlapped span pipeline + lane-parallel queue kernels
        # (ISSUE 16): both static per Manager; pallas_queues is part
        # of the kernel cache key, overlap only gates the driver.
        runner.overlap = self._span_overlap_on()
        runner.pallas_queues = \
            self.config.experimental.pallas_queue_kernels == "on"
        return runner

    def _span_overlap_on(self) -> bool:
        """Resolve `experimental.span_overlap` to the driver gate.

        `auto` speculates only on a real accelerator backend: there
        the device executes the in-flight window asynchronously while
        the host drains/converts, which is the whole point.  On the
        CPU backend the "device" is the same cores the host work
        needs, so a speculative window can never hide behind host
        work — it only adds compute (same reasoning that routes the
        pallas kernels through interpret mode there).  Bytes are
        identical either way; this is wall-side routing only."""
        mode = self.config.experimental.span_overlap
        if mode == "auto":
            import jax
            return jax.default_backend() != "cpu"
        return mode == "on"

    def make_dev_span_runner(self):
        from shadow_tpu.ops.phold_span import PholdSpanRunner
        return self._make_span_runner(PholdSpanRunner)

    def make_tcp_span_runner(self):
        from shadow_tpu.ops.tcp_span import TcpSpanRunner
        return self._make_span_runner(TcpSpanRunner)

    def _device_span(self, start: int, stop: int, limit: int,
                     max_rounds: int, spec_mr: int = 0):
        """Attempt one device-resident multi-round span, routing
        between the PHOLD/udp-mesh family and the TCP steady-stream
        family.  Returns (result, runner); result None = ineligible /
        transient / aborted (the engine state is untouched either way
        — transactional).  `spec_mr > 0` lets a clean commit dispatch
        the next window's speculative async dispatch (ISSUE 16)."""
        args = (start, stop, limit, self.runahead.get(),
                self.runahead.dynamic, max_rounds)
        if self._dev_span is None:
            self._dev_span = self.make_dev_span_runner()
        phold = self._dev_span
        if not phold.ineligible:
            res = phold.try_span(*args, spec_mr=spec_mr)
            if res is not None or not phold.ineligible:
                return res, phold
        # permanently not phold-shaped: the TCP family
        if self._dev_span_tcp is None:
            self._dev_span_tcp = self.make_tcp_span_runner()
        tcp = self._dev_span_tcp
        if tcp.ineligible:
            return None, tcp
        return tcp.try_span(*args, spec_mr=spec_mr), tcp

    def _apply_fault(self, f, at: int, fr_sim) -> None:
        """Apply one `faults:` entry at round boundary `at` — the ONE
        choke point (docs/CHECKPOINT.md): flip the host's fault flags
        on both planes and stamp the FR_FAULT_* flight record.  The
        drop semantics live in the data planes (Host.execute /
        netplane.cpp run_until/deliver/device_push), keyed on these
        flags, so every scheduler applies identical behavior."""
        from shadow_tpu.trace import events as trev
        hid = self._host_by_name[f.host]
        host = self.hosts[hid]
        kind = {
            "host_kill": trev.FR_FAULT_KILL,
            "host_restore": trev.FR_FAULT_RESTORE,
            "link_down": trev.FR_FAULT_LINK_DOWN,
            "link_up": trev.FR_FAULT_LINK_UP,
            "nic_blackhole": trev.FR_FAULT_BLACKHOLE,
            "nic_clear": trev.FR_FAULT_CLEAR,
            "quarantine": trev.FR_FAULT_QUARANTINE,
        }[f.action]
        if f.action == "quarantine":
            # host_kill semantics with containment attribution.
            # IDEMPOTENT: a replayed ledger op landing at the same
            # boundary as the (re-triggered) containment quarantine
            # applies exactly once — whichever fires first records,
            # the other is a silent no-op, so flight/ledger bytes
            # agree between the original and the replay
            # (docs/ROBUSTNESS.md).
            if host.down:
                return
            host.down = True
            if self.containment is not None:
                self.containment.record_op(at, host.name)
        elif f.action == "host_kill":
            host.down = True
        elif f.action == "link_down":
            host.link_down = True
        elif f.action == "link_up":
            host.link_down = False
        elif f.action == "nic_blackhole":
            host.blackhole = True
        elif f.action == "nic_clear":
            host.blackhole = False
        elif f.action == "host_restore":
            from shadow_tpu.ckpt.restore import restore_host
            restore_host(self, f.snapshot, hid, at)
            host = self.hosts[hid]  # replaced by the restore
        if host.plane is not None and f.action != "host_restore":
            # restore_host mirrors its own flags; direct faults mirror
            # here so the engine data plane drops identically.
            self.plane.engine.set_host_fault(
                hid, bool(host.down), bool(host.link_down),
                bool(host.blackhole))
        if fr_sim is not None:
            fr_sim.event(at, kind, hid, 0, 0)
        from shadow_tpu.utils.shadow_log import LOG
        LOG.info(f"fault applied: {f.action} {f.host} at sim "
                 f"{at / 1e9:.6f}s")

    def _apply_quarantine(self, hid: int, at: int, fr_sim) -> None:
        """Apply one containment-triggered quarantine at round
        boundary `at` through the SAME choke point a replayed
        `faults:` quarantine takes (_apply_fault: host_kill machinery,
        FR_FAULT_QUARANTINE, ledger record_op, idempotent on an
        already-down host) — one implementation, so the ledger-replay
        byte-identity contract cannot drift between the two paths."""
        from shadow_tpu.core.config import FaultConfig
        self._apply_fault(
            FaultConfig(at_ns=at, action="quarantine",
                        host=self.hosts[hid].name), at, fr_sim)

    def _log_heartbeat(self, sim_now: int, stop: int, wall_start: float,
                       out) -> None:
        """Progress + resource heartbeat (manager.rs:679-721; the format
        is load-bearing for tornettools-style downstream parsing in the
        reference, so keep it stable once published)."""
        wall = time.perf_counter() - wall_start  # shadow-lint: allow[wall-clock] heartbeat wall-time display
        pct = 100.0 * sim_now / stop if stop else 100.0
        for h in self.hosts:
            h.merge_native_counters()
        events = sum(h.counters["events"] for h in self.hosts)
        packets = sum(h.counters["packets_sent"] for h in self.hosts)
        mem_kb = _rss_kb()
        rate = (sim_now / 1e9) / wall if wall > 0 else 0.0
        print(f"[shadow-tpu] heartbeat: sim {sim_now / 1e9:.3f}s / "
              f"{stop / 1e9:.3f}s ({pct:.1f}%), {rate:.2f} sim-sec/wall-sec, "
              f"events {events}, packets {packets}, rss {mem_kb} kB",
              file=out, flush=True)
        # tornettools-parseable resource lines, format-compatible with
        # the reference's (manager.rs:696-721; tornettools
        # parse_rusage.py matches on these exact phrases).
        import resource as _resource
        ru = _resource.getrusage(_resource.RUSAGE_SELF)
        print(f"Process resource usage at simtime {sim_now} reported by "
              f"getrusage(): "
              f"ru_maxrss={ru.ru_maxrss / (1024 * 1024):.03f} GiB, "
              f"ru_utime={ru.ru_utime / 60:.03f} minutes, "
              f"ru_stime={ru.ru_stime / 60:.03f} minutes, "
              f"ru_nvcsw={ru.ru_nvcsw}, "
              f"ru_nivcsw={ru.ru_nivcsw}",
              file=out, flush=True)
        try:
            mem = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    parts = v.split()
                    if parts and parts[0].isdigit():
                        n = int(parts[0])
                        if len(parts) > 1 and parts[1] == "kB":
                            n *= 1024  # ref converts everything to bytes
                        mem[k.strip()] = n
            print(f"System memory usage in bytes at simtime {sim_now} ns "
                  f"reported by /proc/meminfo: {json.dumps(mem)}",
                  file=out, flush=True)
        except OSError:
            pass

    # ------------------------------------------------------------------
    # Outputs
    # ------------------------------------------------------------------

    def trace_lines(self) -> list[str]:
        lines = []
        for h in self.hosts:
            lines.extend(h.trace_lines())
        return lines

    def write_data_dir(self, summary: SimSummary) -> None:
        base = self.config.general.data_directory
        os.makedirs(base, exist_ok=True)
        # Full re-serialization of the resolved options (defaults and
        # all), re-loadable by from_yaml_text — the reproducibility
        # artifact (manager.rs:183-194).
        import yaml as _yaml
        with open(os.path.join(base, "processed-config.yaml"), "w") as f:
            _yaml.safe_dump(self.config.to_processed_dict(), f,
                            sort_keys=False, default_flow_style=False)
        with open(os.path.join(base, "hosts.txt"), "w") as f:
            f.write(self.dns.hosts_file_text())
        for h in self.hosts:
            hdir = os.path.join(base, "hosts", h.name)
            os.makedirs(hdir, exist_ok=True)
            for proc in h.processes.values():
                stem = os.path.join(hdir, f"{proc.name}.{proc.pid}")
                with open(stem + ".stdout", "wb") as f:
                    f.write(bytes(proc.stdout))
                with open(stem + ".stderr", "wb") as f:
                    f.write(bytes(proc.stderr))
                # Strace files stream directly into the host data dir
                # during the run (Process.strace_write); nothing to copy.
        with open(os.path.join(base, "packet-trace.txt"), "w") as f:
            for line in self.trace_lines():
                f.write(line + "\n")
        from shadow_tpu.utils import object_counter
        from shadow_tpu.utils.shadow_log import LOG
        for kind, delta in object_counter.leaks().items():
            LOG.warning(f"object leak: {delta} {kind} object(s) "
                        f"allocated but never closed")
        LOG.flush()
        syscall_hist: dict[str, int] = {}
        for h in self.hosts:
            for name, n in h.syscall_counts.items():
                syscall_hist[name] = syscall_hist.get(name, 0) + n
        # Span/device dispatch counters (VERDICT r5 weak #5): router
        # regressions — EWMA flapping, always-aborting device spans,
        # a family stuck ineligible — are visible per RUN here, not
        # only on bench stderr.  The block lives in the metrics
        # registry's WALL channel: it measures the scheduler, not the
        # simulation, so the determinism gate strips it structurally
        # (metrics.wall) instead of via a hand-maintained regex list.
        prop = self.propagator
        dispatch = {
            "span_rounds": summary.span_rounds,
            "rounds_dispatched": getattr(prop, "rounds_dispatched", 0),
            "packets_batched": getattr(prop, "packets_batched", 0),
            "rounds_device": getattr(prop, "rounds_device", 0),
            "packets_device": getattr(prop, "packets_device", 0),
            # Effective engine-pcap span cap (the experimental.
            # pcap_span_cap knob; 1024 = no engine-pcap capture, the
            # generic clamp applied).
            "pcap_span_cap": (self.config.experimental.pcap_span_cap
                              if self._pcap_engine else 1024),
            # Overlapped span pipeline (ISSUE 16): the effective knob
            # values the router ran with (the dev_span_k_* heuristics
            # and the overlap/pallas modes) — wall-side routing
            # telemetry, like pcap_span_cap.
            "span_overlap": self.config.experimental.span_overlap,
            "pallas_queue_kernels":
                self.config.experimental.pallas_queue_kernels,
            "dev_span_k": {
                "init": self.config.experimental.dev_span_k_init,
                "floor": self.config.experimental.dev_span_k_floor,
                "shrink": self.config.experimental.dev_span_k_shrink,
            },
        }
        if getattr(prop, "n_shards", 1) > 1:
            # Sharded per-round path: the on-device exchange's packet
            # split and its wall (the all_to_all dispatch+sync leg),
            # credited here so bench's headline JSON shows where the
            # sharded rounds' wall goes (ISSUE 11 satellite).
            dispatch["shards"] = prop.n_shards
            dispatch["packets_exchanged"] = prop.packets_exchanged
            dispatch["packets_overflowed"] = prop.packets_overflowed
            dispatch["exchange_wall_s"] = round(
                getattr(prop, "exchange_wall_ns", 0) / 1e9, 6)
        fn_cache = {}
        for family, runner in (("phold", getattr(self, "_dev_span",
                                                 None)),
                               ("tcp", getattr(self, "_dev_span_tcp",
                                               None))):
            if runner is not None:
                dispatch[f"device_span_{family}"] = {
                    "spans": runner.spans,
                    "rounds": runner.rounds,
                    "micro_iters": getattr(runner, "micro_iters", 0),
                    "aborts": runner.aborts,
                    "ineligible": runner.ineligible,
                    "transient_or_over_caps": runner.over_caps,
                    "resident_hits": getattr(runner,
                                             "resident_hits", 0),
                    "stale_drops": getattr(runner, "stale_drops", 0),
                    # Sharded span placement (ISSUE 11): mesh width
                    # the kernels built for, the live exchange
                    # capacity, and how often AB_EXCH grew it.
                    "shards": getattr(runner, "n_shards", 1),
                    "exchange_cap": getattr(runner, "exchange_cap",
                                            0),
                    "exchange_grows": getattr(runner, "exch_grows",
                                              0),
                    # Device-kernel observatory wall side (ISSUE 15):
                    # dispatch wall, the speculative-window rollback
                    # ledger (aborted dispatch wall + forced
                    # re-exports + stepped-then-discarded rounds, by
                    # abort kind) and the codec byte volume per
                    # direction.  All wall-channel: the det gate
                    # strips them structurally.
                    "dispatch_wall_s": round(
                        getattr(runner, "device_wall_ns", 0) / 1e9, 6),
                    "rolled_back_rounds": getattr(
                        runner, "rolled_back_rounds", 0),
                    "rollback_wall_s": round(
                        getattr(runner, "rollback_wall_ns", 0) / 1e9,
                        6),
                    "rollback_reexport_wall_s": round(
                        getattr(runner, "rollback_reexport_ns", 0)
                        / 1e9, 6),
                    "abort_kinds": dict(runner.abort_kind_counts()),
                    "export_bytes": getattr(runner, "export_bytes", 0),
                    "import_bytes": getattr(runner, "import_bytes", 0),
                    # Overlap counters (ISSUE 16): speculative windows
                    # dispatched/landed/refused and the host/device
                    # idle walls of the landed pipe — what `trace
                    # kern`'s overlap report and bench's per-rung
                    # overlap block read.
                    "overlap": runner.overlap_summary(),
                }
                if getattr(runner, "kernel_costs", None):
                    # Compiled.cost_analysis() per AOT-built kernel
                    # (kernel_observatory wall/on, unsharded).
                    dispatch[f"device_span_{family}"][
                        "kernel_costs"] = list(runner.kernel_costs)
                fn_cache[family] = {
                    "hits": getattr(runner, "fn_cache_hits", 0),
                    "misses": getattr(runner, "fn_cache_misses", 0),
                    "build_wall_s": round(
                        getattr(runner, "fn_cache_build_ns", 0) / 1e9,
                        6),
                }
        if fn_cache:
            # Explicit _FN_CACHE accounting (was the _timed_fns
            # compile-vs-execute heuristic): hits/misses/build wall
            # per span family, shared via ops/span_mesh.py.
            dispatch["fn_cache"] = fn_cache
        reg = self.metrics
        reg.ingest("dispatch", dispatch, channel="wall")
        if self.svc is not None:
            # Syscall service plane: worker count + host-rounds
            # drained (wall-side scheduling telemetry, like dispatch).
            reg.ingest("svc", self.svc.wall_summary(), channel="wall")
        # Sim-netstat drop attribution (always on): one TEL_* cause
        # per drop on every execution path, so these counters are
        # deterministic AND path-identical — they live in the SIM
        # channel and the determinism gate byte-diffs them.  The
        # conservation contract (docs/PARITY.md): wire causes sum to
        # packets_dropped; the two TCP receiver discards sit outside
        # (their packets were delivered, only payload was refused).
        reg.ingest("netstat.drops", self.drop_cause_totals(),
                   channel="sim")
        if self.netstat is not None:
            reg.gauge("netstat.records", channel="sim").set(
                self.netstat.records)
            reg.gauge("netstat.dropped", channel="sim").set(
                self.netstat.dropped)
            self.netstat.write(base)
        # Fabric observatory: the conservation counters are always on
        # and live in the SIM channel (deterministic AND
        # path-identical — the gate byte-diffs them; `violations`
        # nonzero means an interface lost bytes the TEL_* causes
        # cannot explain, which the det gate and the incast smoke
        # reject).  The sample channel and the flow records only
        # exist when the knob is on.
        reg.ingest("fabric", self.fabric_conservation(), channel="sim")
        if self.fabric is not None:
            reg.gauge("fabric.records", channel="sim").set(
                self.fabric.records)
            reg.gauge("fabric.dropped", channel="sim").set(
                self.fabric.dropped)
            fct_rows = self.collect_fct_rows()
            reg.gauge("fabric.flows", channel="sim").set(len(fct_rows))
            self.fabric.write(base, fct_rows)
        # Device-kernel observatory: one KS_REC per committed device
        # span; record/drop counts live in the SIM channel (the gate
        # byte-diffs them) and the artifact is byte-diffed like every
        # sim channel.  A run with no device spans writes an empty
        # artifact — scheduler-identical by construction.
        if self.kern is not None:
            reg.gauge("kern.records", channel="sim").set(
                self.kern.records)
            reg.gauge("kern.dropped", channel="sim").set(
                self.kern.dropped)
            self.kern.write(base)
        # Syscall observatory: disposition counters are always on and
        # live in the SIM channel (deterministic per config; the gate
        # byte-diffs them — engine-resident apps dispatch C++-side and
        # are documented outside this accounting).  The wall-time IPC
        # profile and the record channel only exist when the knob is
        # wall/on.
        reg.ingest("syscalls.dispositions", self.sc_disposition_totals(),
                   channel="sim")
        if self.sctrace is not None:
            self.sctrace.ingest_metrics(reg)
            self.sctrace.write(base)
        # Fault ledger (svc/containment.py, docs/ROBUSTNESS.md): the
        # containment plane's record of every containment action.
        # `ops` is a ready-to-paste `faults:` schedule (the replay
        # contract); `events` carries causes.  Deterministic content —
        # sim-time stamps and canonical sort only.
        if self.containment is not None:
            ledger = self.containment.ledger()
            with open(os.path.join(base, "fault-ledger.json"),
                      "w") as f:
                json.dump(ledger, f, indent=1, sort_keys=True)
            reg.gauge("containment.quarantines", channel="sim").set(
                len(ledger["ops"]))
        # One reason code per conservative round (trace/audit.py);
        # tools/trace renders this as the attribution report.
        reg.ingest("eligibility", self.audit.as_dict(), channel="wall")
        if self.flight is not None:
            reg.ingest("phases",
                       {name: ns for name, (ns, _c) in
                        self.flight.wall.phases.items()},
                       channel="wall")
            sim = self.flight.sim
            reg.gauge("flight.sim_records", channel="sim").set(
                sim.records if sim is not None else 0)
            reg.gauge("flight.sim_dropped", channel="sim").set(
                sim.dropped if sim is not None else 0)
            self.flight.write(base)
        stats = {
            "end_time_ns": summary.end_time_ns,
            "rounds": summary.rounds,
            "events": summary.events,
            "packets_sent": summary.packets_sent,
            "packets_recv": summary.packets_recv,
            "packets_dropped": summary.packets_dropped,
            "syscalls": summary.syscalls,
            "syscalls_by_name": syscall_hist,
            "metrics": reg.as_stats(),
            "objects": object_counter.snapshot(),
            "hosts": {h.name: dict(h.counters) for h in self.hosts},
        }
        if self._perf_timers:
            stats["perf"] = {"host_exec_ns":
                             {h.name: h.perf_exec_ns for h in self.hosts}}
        with open(os.path.join(base, "sim-stats.json"), "w") as f:
            json.dump(stats, f, indent=2, sort_keys=True)


def _topology_cpu_order(cpus: list[int]) -> list[int]:
    """NUMA/SMT-aware worker CPU ordering (ref: affinity.c:1-464 —
    the reference parses /sys topology to pick "good" worker CPUs).

    Order: one logical CPU per PHYSICAL core first (hyperthread
    siblings share execution units — two workers on one core is the
    last resort), physical cores interleaved round-robin across NUMA
    nodes (spreads memory traffic over controllers), then the
    remaining SMT siblings in the same node-interleaved order.
    Falls back to the input order when /sys is unreadable."""
    def read_int(path: str) -> int:
        try:
            with open(path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return 0

    # cpu -> NUMA node (node directories own cpuN symlinks; reverse
    # lookup via .../cpuN/node* is not always present, so scan).
    cpu_node: dict[int, int] = {}
    try:
        for entry in os.listdir("/sys/devices/system/node"):
            if not entry.startswith("node") or not entry[4:].isdigit():
                continue
            node = int(entry[4:])
            for sub in os.listdir(f"/sys/devices/system/node/{entry}"):
                if sub.startswith("cpu") and sub[3:].isdigit():
                    cpu_node[int(sub[3:])] = node
    except OSError:
        pass

    core_seen: set[tuple] = set()
    primaries: list[tuple] = []   # (node, pkg, core, cpu)
    siblings: list[tuple] = []
    for cpu in cpus:
        base = f"/sys/devices/system/cpu/cpu{cpu}/topology"
        pkg = read_int(f"{base}/physical_package_id")
        core = read_int(f"{base}/core_id")
        key = (pkg, core)
        row = (cpu_node.get(cpu, 0), pkg, core, cpu)
        if key in core_seen:
            siblings.append(row)
        else:
            core_seen.add(key)
            primaries.append(row)

    def node_interleave(rows: list[tuple]) -> list[int]:
        by_node: dict[int, list[int]] = {}
        for node, _pkg, _core, cpu in sorted(rows):
            by_node.setdefault(node, []).append(cpu)
        out: list[int] = []
        queues = [by_node[n] for n in sorted(by_node)]
        while any(queues):
            for q in queues:
                if q:
                    out.append(q.pop(0))
        return out

    ordered = node_interleave(primaries) + node_interleave(siblings)
    return ordered if ordered else cpus


def _make_pinner():
    """Worker-thread CPU pinning (ref: affinity.c; unpinned runs cost
    up to ~3x, docs/parallel_sims.md:14-16).  Workers claim CPUs in
    the topology-aware order above."""
    import itertools
    import threading

    try:
        cpus = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return None
    if not cpus:
        return None
    cpus = _topology_cpu_order(cpus)
    counter = itertools.count()
    lock = threading.Lock()

    def pin():
        with lock:
            i = next(counter)
        try:
            os.sched_setaffinity(0, {cpus[i % len(cpus)]})
        except OSError:
            pass

    return pin


def _rss_kb() -> int:
    """Resident set size from /proc (ref: resource_usage.rs meminfo)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


def run_simulation(config: ConfigOptions, write_data: bool = False):
    """run_shadow equivalent (src/main/shadow.rs:30)."""
    manager = Manager(config)
    summary = manager.run()
    if write_data:
        manager.write_data_dir(summary)
    return manager, summary


def resume_simulation(config: ConfigOptions, snapshot: str,
                      write_data: bool = False):
    """Resume a snapshotted simulation mid-run (shadow_tpu/ckpt/,
    docs/CHECKPOINT.md): rebuild the Manager from config, restore the
    archive over it, and continue the round loop — every byte-diffed
    artifact is a continuation of the straight run's."""
    from shadow_tpu.ckpt.restore import resume_manager
    manager = resume_manager(config, snapshot)
    summary = manager.run()
    if write_data:
        manager.write_data_dir(summary)
    return manager, summary
