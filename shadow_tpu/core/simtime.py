"""Simulation time primitives.

Two clocks, as in the reference's shadow-shim-helper-rs
(src/lib/shadow-shim-helper-rs/src/simulation_time.rs and emulated_time.rs):

- *simulation time*: nanoseconds since the start of the simulation (t=0).
- *emulated time*: nanoseconds since the UNIX epoch as seen by managed
  code; the simulation starts at a fixed epoch so runs are reproducible
  regardless of the real wallclock.

Times are plain Python ints on the host path (arbitrary precision, cheap)
and int64 arrays on the device path. We deliberately do not wrap them in
classes: the event loop compares and adds times millions of times per
round and attribute indirection is pure overhead under CPython.
"""

NSEC_PER_USEC = 1_000
NSEC_PER_MSEC = 1_000_000
NSEC_PER_SEC = 1_000_000_000

# The simulated UNIX epoch at simulation time 0: 2000-01-01 00:00:00 UTC.
# A fixed, plausible-but-clearly-simulated date (same policy as the
# reference's EmulatedTime SIMULATION_START).
EMUTIME_SIMULATION_START = 946_684_800 * NSEC_PER_SEC

# Sentinel for "no event pending" / "never": must compare greater than any
# reachable time and fit in int64 for device-side min-reductions.
TIME_NEVER = (1 << 62)

SIMTIME_INVALID = -1


def emulated_from_sim(sim_ns: int) -> int:
    """Emulated (wall-looking) time for a simulation instant."""
    return EMUTIME_SIMULATION_START + sim_ns


def sim_from_emulated(emu_ns: int) -> int:
    return emu_ns - EMUTIME_SIMULATION_START


def fmt(sim_ns: int) -> str:
    """Human formatting for logs: seconds with ns precision."""
    if sim_ns >= TIME_NEVER:
        return "never"
    return f"{sim_ns // NSEC_PER_SEC}.{sim_ns % NSEC_PER_SEC:09d}s"
