"""Events, tasks, and the per-host event queue.

Mirrors the reference's deterministic total order on events
(src/main/core/work/event.rs:10-63): events sort by

    (time, packet-before-local, source host id, per-source sequence number)

so that two runs — and two *schedulers* (scalar CPU vs batched TPU) —
dispatch identical event interleavings. The per-source sequence number is
assigned by the sending host at push time, which keeps ordering decisions
local (no global atomic), exactly the property that lets hosts run in
parallel within a round.

The queue itself is a binary heap (src/main/core/work/event_queue.rs:10-54)
with the same monotonic-pop assertion the reference carries.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Optional

# Event kinds: packets sort before local tasks at equal times
# (event.rs:41-53 gives packets priority so cross-host interleavings are
# independent of which host pushed first).
KIND_PACKET = 0
KIND_LOCAL = 1


class TaskRef:
    """A named host-local callback (ref: src/main/core/work/task.rs:12-44)."""

    __slots__ = ("fn", "name", "args")

    def __init__(self, name: str, fn: Callable, *args):
        self.fn = fn
        self.name = name
        self.args = args

    def execute(self, host) -> None:
        self.fn(host, *self.args)

    def __repr__(self) -> str:
        return f"TaskRef({self.name})"


class Event:
    __slots__ = ("time", "kind", "src_host_id", "seq", "data")

    def __init__(self, time: int, kind: int, src_host_id: int, seq: int, data: Any):
        self.time = time
        self.kind = kind
        self.src_host_id = src_host_id
        self.seq = seq
        self.data = data  # Packet for KIND_PACKET, TaskRef for KIND_LOCAL

    def sort_key(self):
        return (self.time, self.kind, self.src_host_id, self.seq)

    def __lt__(self, other: "Event") -> bool:
        return self.sort_key() < other.sort_key()

    def __repr__(self) -> str:
        k = "pkt" if self.kind == KIND_PACKET else "task"
        return f"Event(t={self.time}, {k}, src={self.src_host_id}, seq={self.seq})"


class EventQueue:
    """Min-heap of events for one host.

    Only the owning host pops; cross-host pushes are serialized by the
    scheduler (CPU: a mutex per queue as in worker.rs:597-607; TPU: the
    batched exchange delivers all pushes between rounds, so no lock is
    needed at all — a structural win of the round-synchronous design).
    """

    __slots__ = ("_heap", "_last_popped_time")

    def __init__(self):
        # Heap entries are (time, kind, src_host_id, seq, event) tuples:
        # heapq then compares native ints instead of calling
        # Event.__lt__ (millions of Python-level calls per run).  The
        # (src_host_id, seq) pair is unique per source, so comparison
        # never falls through to the Event object itself.
        self._heap: list[tuple] = []
        self._last_popped_time = 0

    def push(self, event: Event) -> None:
        heapq.heappush(self._heap, (event.time, event.kind,
                                    event.src_host_id, event.seq, event))

    def peek_time(self) -> Optional[int]:
        return self._heap[0][0] if self._heap else None

    def pop(self) -> Optional[Event]:
        if not self._heap:
            return None
        ev = heapq.heappop(self._heap)[4]
        # Determinism guard (event_queue.rs:33): time must never go backwards.
        assert ev.time >= self._last_popped_time, (
            f"event time moved backwards: {ev} after t={self._last_popped_time}")
        self._last_popped_time = ev.time
        return ev

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:
        return bool(self._heap)
