"""Deterministic, order-independent randomness.

The reference draws per-packet loss decisions from a *sequential* per-host
xoshiro256++ stream (src/main/host/host.rs:166 `random`, used in
src/main/core/worker.rs:357-368). A sequential stream is hostile to
batching: the value drawn for a packet depends on how many draws happened
before it, i.e. on execution order. Our design replaces every such draw
with a *counter-based* RNG (threefry2x32, Salmon et al., SC'11 — the same
family JAX uses natively) keyed by the packet's identity:

    bits = threefry2x32(key=(seed, stream), ctr=(src_host_id, packet_seq))

so the scalar CPU path and the batched TPU path compute bit-identical
decisions no matter in which order packets are processed. This is the
keystone of the byte-identical-trace requirement (BASELINE.md).

The same core is implemented once, generically over numpy and jax.numpy;
`tests/test_rng.py` asserts bit-equality between the two backends and
against the published threefry2x32 test vectors.
"""

from __future__ import annotations

import numpy as np

# Threefry constants (public algorithm specification).
_PARITY = 0x1BD11BDA
_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)


def _threefry2x32(xp, k0, k1, c0, c1):
    """20-round threefry2x32. All inputs/outputs uint32 arrays of one shape.

    `xp` is numpy or jax.numpy; both wrap uint32 arithmetic mod 2**32.
    """
    u32 = xp.uint32

    def rotl(x, r):
        return (x << u32(r)) | (x >> u32(32 - r))

    k2 = k0 ^ k1 ^ u32(_PARITY)
    ks = (k0, k1, k2)
    x0 = (c0 + k0).astype(xp.uint32)
    x1 = (c1 + k1).astype(xp.uint32)
    for d in range(5):  # 5 groups x 4 rounds = 20
        for r in _ROT_A if d % 2 == 0 else _ROT_B:
            x0 = (x0 + x1).astype(xp.uint32)
            x1 = rotl(x1, r) ^ x0
        x0 = (x0 + ks[(d + 1) % 3]).astype(xp.uint32)
        x1 = (x1 + ks[(d + 2) % 3] + u32(d + 1)).astype(xp.uint32)
    return x0, x1


def threefry2x32_np(k0, k1, c0, c1):
    """Numpy backend; scalar or array uint32 inputs -> (uint32, uint32)."""
    arrs = [np.asarray(v, dtype=np.uint32) for v in (k0, k1, c0, c1)]
    with np.errstate(over="ignore"):
        return _threefry2x32(np, *arrs)


def threefry2x32_jax(k0, k1, c0, c1):
    """JAX backend; traceable, for use inside jitted kernels."""
    import jax.numpy as jnp

    return _threefry2x32(jnp, k0.astype(jnp.uint32), k1.astype(jnp.uint32),
                         c0.astype(jnp.uint32), c1.astype(jnp.uint32))


# ---------------------------------------------------------------------------
# Stream identifiers: disjoint key-spaces for independent uses of the seed.
# ---------------------------------------------------------------------------
STREAM_PACKET_LOSS = 1
STREAM_HOST = 2  # per-host general-purpose stream (ports, auxv, jitter)
STREAM_JITTER = 3
STREAM_EXAMPLE_BATCH = 101  # synthetic dry-run inputs (parallel/round_step)
STREAM_RPC_SIZE = 102  # heavy-tailed RPC sizes (tools/netgen rpc_burst)
STREAM_SURROGATE = 103  # GNN parameter init (surrogate/model.py)


def mix_key(seed: int, stream: int):
    """Fold (seed, stream) into a 2x32 threefry key (host-side, cheap)."""
    k = (seed * 0x9E3779B97F4A7C15 + stream) & 0xFFFFFFFFFFFFFFFF
    return (k & 0xFFFFFFFF, k >> 32)


def loss_threshold_u32(probability: float) -> int:
    """Integer comparison threshold for `drop iff bits < threshold`.

    Computed once on the host in float64 so both backends compare the same
    integer; avoids any float-rounding divergence between CPU and TPU.

    Contract: the returned value is in [0, 2**32] and therefore does NOT
    fit in uint32 when probability is 1.0 — the comparison must be done in
    >=33-bit arithmetic. Kernels cast the uint32 bits to int64 before
    comparing (see ops/propagate.py); host-side Python-int comparison is
    naturally exact.
    """
    if probability <= 0.0:
        return 0
    if probability >= 1.0:
        return 1 << 32
    return int(probability * float(1 << 32))


def threefry2x32_py(k0: int, k1: int, c0: int, c1: int) -> tuple[int, int]:
    """Pure-Python-int threefry2x32 — bit-identical to the array backends.

    Used by `HostRng` on the scalar hot path: per-draw numpy scalar
    dispatch costs ~10x more than plain int arithmetic for a 20-round
    block cipher. Cross-checked against the numpy backend in tests.
    """
    M = 0xFFFFFFFF
    k2 = k0 ^ k1 ^ _PARITY
    ks = (k0, k1, k2)
    x0 = (c0 + k0) & M
    x1 = (c1 + k1) & M
    for d in range(5):
        for r in _ROT_A if d % 2 == 0 else _ROT_B:
            x0 = (x0 + x1) & M
            x1 = (((x1 << r) & M) | (x1 >> (32 - r))) ^ x0
        x0 = (x0 + ks[(d + 1) % 3]) & M
        x1 = (x1 + ks[(d + 2) % 3] + d + 1) & M
    return x0, x1


def packet_loss_bits_np(seed: int, src_host_id, packet_seq):
    """Loss-decision bits for packets identified by (src_host, seq) (numpy)."""
    k0, k1 = mix_key(seed, STREAM_PACKET_LOSS)
    b0, _ = threefry2x32_np(np.uint32(k0), np.uint32(k1),
                            np.asarray(src_host_id, np.uint32),
                            np.asarray(packet_seq, np.uint32))
    return b0


class HostRng:
    """Stateful counter-based stream for one host.

    Replaces the reference's per-host xoshiro256++ (host.rs:166) for
    host-local randomness (ephemeral ports, app-visible random bytes).
    State is just (key, counter); cheap to snapshot for checkpointing.
    """

    __slots__ = ("_k0", "_k1", "_host_id", "_counter", "_engine")

    def __init__(self, seed: int, host_id: int):
        k0, k1 = mix_key(seed, STREAM_HOST)
        self._k0 = k0 ^ (host_id & 0xFFFFFFFF)
        self._k1 = k1 ^ (host_id >> 32)
        self._host_id = host_id
        self._counter = 0
        self._engine = None  # native-plane delegate (ONE shared counter)

    def attach_engine(self, engine, hid: int) -> None:
        """Delegate draws to the data-plane engine's native threefry:
        the engine registered (key, counter) via set_host_rng, and from
        here on it owns the stream position."""
        self._engine = engine

    def __getstate__(self):
        # Checkpoint (shadow_tpu/ckpt/): the engine delegate is
        # re-attached on restore; an engine-owned stream's position
        # travels in the plane blob, an object-path stream's in
        # _counter here.
        return (self._k0, self._k1, self._host_id, self._counter)

    def __setstate__(self, state):
        self._k0, self._k1, self._host_id, self._counter = state
        self._engine = None

    def next_u64(self) -> int:
        if self._engine is not None:
            return self._engine.rng_next(self._host_id)
        b0, b1 = threefry2x32_py(self._k0, self._k1,
                                 self._counter & 0xFFFFFFFF,
                                 self._counter >> 32)
        self._counter += 1
        return (b1 << 32) | b0

    def next_u32(self) -> int:
        return self.next_u64() & 0xFFFFFFFF

    def uniform(self) -> float:
        """Float64 in [0, 1). Uses the top 53 bits so the scaled value can
        never round up to exactly 1.0."""
        return (self.next_u64() >> 11) * (2.0 ** -53)

    def randrange(self, lo: int, hi: int) -> int:
        """Integer in [lo, hi); unbiased enough for simulation purposes."""
        return lo + self.next_u64() % (hi - lo)

    def bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += self.next_u64().to_bytes(8, "little")
        return bytes(out[:n])
