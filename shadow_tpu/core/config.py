"""Simulation configuration: YAML schema + CLI overrides.

Mirrors the reference's config surface (src/main/core/configuration.rs;
docs/shadow_config_spec.md): `general` / `network` / `experimental` /
`hosts` sections, SI-unit values, `x-` extension keys ignored, YAML merge
keys honored (pyyaml resolves `<<` natively). The `experimental.scheduler`
switch grows a `tpu` variant next to the reference's thread-per-core /
thread-per-host choices (configuration.rs:938) — that switch is the whole
point of this framework.

Process `path` may name a real binary (interposition backend, later
rounds) or a *registered internal app* (host/apps.py) — the internal
traffic-generator workloads used by the benchmark configs resolve there
first, the way the reference points configs at tgen binaries.
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field
from typing import Any

import yaml

from shadow_tpu.net import graph as netgraph
from shadow_tpu.utils import units

SCHEDULERS = ("thread_per_core", "thread_per_host", "serial", "tpu")
QDISC_MODES = ("fifo", "round_robin")


ON_FAILURE_POLICIES = ("abort", "quarantine", "restart")


@dataclass
class ProcessConfig:
    path: str
    args: list[str] = field(default_factory=list)
    environment: dict[str, str] = field(default_factory=dict)
    start_time_ns: int = 0
    shutdown_time_ns: int | None = None
    shutdown_signal: str = "SIGTERM"
    expected_final_state: Any = "exited 0"
    # Failure containment policy (docs/ROBUSTNESS.md): what the sim
    # does when this process fails against its expected final state —
    # unexpected binary death, a hang past the wall watchdog, or a
    # spawn failure after the bounded EAGAIN/ENOMEM retries.
    #   abort       keep today's semantics: record a plugin error (the
    #               run completes but summary.ok is False).
    #   quarantine  contain the failure: the host is killed (host_kill
    #               machinery, FR_FAULT_QUARANTINE attribution) at the
    #               next conservative-round boundary and the action is
    #               appended to the fault ledger.
    #   restart     re-spawn the binary at the failure instant, up to
    #               restart_budget times; exhaustion quarantines.
    on_failure: str = "abort"
    restart_budget: int = 2


@dataclass
class HostConfig:
    name: str
    network_node_id: int
    processes: list[ProcessConfig]
    ip_addr: int | None = None
    bandwidth_down_bits: int | None = None  # overrides graph-node default
    bandwidth_up_bits: int | None = None
    pcap_enabled: bool = False
    pcap_capture_size: int = 65535
    # Per-host engine opt-out: False pins this host to the pure-Python
    # object path (debugging aid; traces are byte-identical either way
    # — the cross-plane interop gates are the proof).
    native_dataplane: bool = True
    # Per-host TCP stack (`tcp: {cc: reno|dctcp, ecn: on|off}`): the
    # congestion controller every connection on this host runs, and
    # whether its handshakes offer/accept ECN.  DCTCP without ECN is
    # plain reno-shaped (no echo ever arrives), so the loader warns by
    # rejecting that combination.
    tcp_cc: str = "reno"
    tcp_ecn: bool = False


@dataclass
class GeneralConfig:
    stop_time_ns: int = 0
    seed: int = 1
    bootstrap_end_time_ns: int = 0
    parallelism: int = 0  # 0 = auto (num cores)
    data_directory: str = "shadow.data"
    template_directory: str | None = None
    progress: bool = False
    heartbeat_interval_ns: int = units.parse_time_ns("1 s")
    log_level: str = "info"
    # Divergence from the reference's default (false): our managed-
    # process timing baselines are built on the model being active,
    # and it is what serializes syscall-spinning code into the
    # deterministic timeline.  Set false to disable.
    model_unblocked_syscall_latency: bool = True


@dataclass
class NetworkConfig:
    graph: netgraph.NetworkGraph = None
    use_shortest_path: bool = True


@dataclass
class CheckpointConfig:
    """`checkpoint:` block (docs/CHECKPOINT.md): snapshot the
    simulation at the first conservative-round boundary at or after
    each listed time.  Presence of the block also turns on syscall-
    transcript recording for internal apps (the object path's
    generator frames resume through replay)."""
    at_ns: list[int] = field(default_factory=list)
    directory: str | None = None  # default: <data_directory>/ckpt


FAULT_ACTIONS = ("host_kill", "host_restore", "link_down", "link_up",
                 "nic_blackhole", "nic_clear", "quarantine")


@dataclass
class FaultConfig:
    """One `faults:` entry: applied deterministically at the first
    round boundary at or after `at` through the manager's single
    fault choke point (docs/CHECKPOINT.md "Fault injection")."""
    at_ns: int
    action: str       # one of FAULT_ACTIONS
    host: str         # target host name
    snapshot: str | None = None  # host_restore: archive path


@dataclass
class ExperimentalConfig:
    scheduler: str = "thread_per_core"
    runahead_ns: int | None = None  # None = auto (graph min latency)
    use_dynamic_runahead: bool = False
    interface_qdisc: str = "fifo"
    socket_send_buffer: int = 131_072
    socket_recv_buffer: int = 174_760
    # Dynamic buffer sizing (ref configuration.rs:564-566, default on;
    # algorithm from tcp.c _tcp_autotuneReceiveBuffer/SendBuffer).
    socket_send_autotune: bool = True
    socket_recv_autotune: bool = True
    strace_logging_mode: str = "off"  # off | standard | deterministic
    max_unapplied_cpu_latency_ns: int = units.parse_time_ns("20 us")
    unblocked_syscall_latency_ns: int = units.parse_time_ns("1 us")
    # Host CPU model (ref cpu.rs; off by default like sim_config.rs:246)
    host_cpu_threshold_ns: int | None = None
    host_cpu_precision_ns: int | None = None
    host_cpu_event_cost_ns: int = 0  # modeled CPU ns charged per event
    # Native preemption (ref preempt.rs + configuration.rs:510-527):
    # regain control from managed code spinning on pure CPU.  Makes
    # event timing depend on native CPU speed — NON-deterministic —
    # hence off by default, like the reference.
    native_preemption_enabled: bool = False
    native_preemption_native_interval_ns: int = units.parse_time_ns("10 ms")
    native_preemption_sim_interval_ns: int = units.parse_time_ns("10 ms")
    # Modeled bandwidth for native file I/O in managed processes (file
    # reads/writes execute on the real fs but bill simulated CPU time
    # at this rate so disk-bound phases shape the timeline; active only
    # while model_unblocked_syscall_latency is on; 0 disables).
    native_file_io_bandwidth_bps: int = units.parse_bytes("1 GiB")
    unblocked_vdso_latency_ns: int = units.parse_time_ns("10 ns")
    tpu_max_packets_per_round: int = 1 << 20
    # Below this, propagation always runs the numpy host path; above,
    # the online cost model measures host vs device and routes.
    tpu_min_device_batch: int = 2048
    # Host shards for the multi-device mesh backend: >1 partitions hosts
    # across that many devices (jax.sharding.Mesh over the 'hosts' axis)
    # and runs the SPMD round step (parallel/round_step.py). 1 = single
    # device (TpuPropagator).
    tpu_shards: int = 1
    # Fixed per-shard-pair packet capacity of the all_to_all exchange
    # (static shape). Overflow is delivered host-side — a performance
    # fallback, never a correctness one.
    tpu_exchange_capacity: int = 1 << 12
    # Native (C++) data plane for scheduler=tpu: "auto" uses it when the
    # extension builds, "on" requires it (error if unavailable), "off"
    # forces the pure-Python object path.  Hosts with pcap capture or a
    # CPU model fall back to the object path individually; traces are
    # byte-identical either way (the cross-scheduler determinism gates
    # are the parity proof).
    native_dataplane: str = "auto"
    # Device-resident multi-round spans (ops/phold_span.py): whole
    # conservative windows step ON DEVICE as struct-of-arrays for
    # eligible (PHOLD-pure) sims.  "auto" measures device vs C++ span
    # throughput and routes; "force" always takes the device when
    # eligible (parity gates, demonstrations); "off" disables.
    tpu_device_spans: str = "auto"
    # Device-span carry donation (donate_argnums=0: XLA reuses the
    # resident carry's buffers in place).  OFF by default: a donated
    # executable loaded back from the PERSISTENT XLA compilation cache
    # corrupts the glibc heap on deserialization-hit runs (BASELINE.md
    # round 6, reproduced with MALLOC_CHECK_ on the CPU backend).  "on"
    # re-lands donation behind a compile-cache-safe guard: the span
    # runners donate ONLY when no persistent compilation cache is
    # configured (jax_compilation_cache_dir unset), and fall back to
    # undonated dispatch otherwise — never the corrupting combination.
    tpu_donate_buffers: str = "off"
    # Overlapped span pipeline (docs/OBSERVABILITY.md "Overlapped
    # pipeline"): "on" double-buffers the device-span dispatch — after
    # a window commits, the NEXT speculative window is dispatched
    # asynchronously (jax async dispatch, no block) and the host-side
    # import/codec/service work for the committed window runs while
    # the device executes.  The in-flight record carries the window
    # bounds and the pre-dispatch engine state_epoch; on landing it
    # commits only if the bounds match and the epoch is unchanged —
    # any drift refuses the window (discarded unimported), so all five
    # sim channels stay byte-identical by construction.  "off" keeps
    # the strictly serial dispatch.  Wall-side only; digest-skipped.
    span_overlap: str = "auto"
    # Lane-parallel queue-scan kernels (ops/pallas_queues.py): "on"
    # routes the token-bucket refill/conformance scan and the CoDel
    # head classification of both span families through pallas
    # kernels (interpret mode on the CPU backend, so tier-1 still
    # runs them); "off" keeps the inline lax forms.  Integer-exact
    # either way — byte identity is gated, not assumed.
    pallas_queue_kernels: str = "off"
    # Speculative-window heuristics for the device-span router
    # (core/manager.py), promoted from hard-coded constants:
    # the starting window in rounds...
    dev_span_k_init: int = 32
    # ...the floor the window never shrinks below after an abort...
    dev_span_k_floor: int = 16
    # ...and the divisor applied on each abort (the 2x growth cap on
    # clean commits stays fixed).  All three are wall-side routing
    # only (never reach simulation bytes) and digest-skipped; the
    # effective values surface in metrics.wall.dispatch.
    dev_span_k_shrink: int = 4
    # Deterministic flight recorder (shadow_tpu/trace/,
    # docs/OBSERVABILITY.md): "on" records both channels (sim-time
    # event stream + wall-time phases -> flight-sim.bin /
    # flight-wall.json in the data dir), "wall" records phase timings
    # only (what bench.py uses), "off" records nothing.  The
    # device-eligibility audit and the metrics registry run regardless
    # (cheap counters, always in sim-stats.json).
    flight_recorder: str = "off"
    # Sim-netstat (docs/OBSERVABILITY.md "sim-netstat"): "on" records
    # the deterministic per-connection TCP telemetry channel
    # (telemetry-sim.bin: cwnd/ssthresh/srtt/RTO/buffers/retransmits
    # per connection per sampled round, byte-identical across runs AND
    # across the three execution paths).  The packet-drop attribution
    # counters (metrics.sim.netstat.drops) run regardless — cheap
    # integer adds, always in sim-stats.json.
    sim_netstat: str = "off"
    # Sim-netstat sampling grid in simulated ns: a conservative round
    # [start, end) emits samples iff it crosses a grid boundary
    # (start // interval != end // interval).  0 = every round.
    netstat_interval_ns: int = 0
    # Fabric observatory (docs/OBSERVABILITY.md "Fabric
    # observatory"): "on" records the deterministic per-link queue
    # telemetry + flow-completion-time channel (fabric-sim.bin: CoDel
    # depth/sojourn/drop counters, token-bucket occupancy and refill
    # stalls, per-link bytes/packets per active host per sampled
    # round, plus per-flow lifecycle records — byte-identical across
    # runs AND across the three execution paths).  The conservation
    # counters (metrics.sim.fabric.*: bytes/packets enqueued ==
    # delivered + dropped + queued per interface) run regardless —
    # cheap integer adds, like drop attribution.
    sim_fabricstat: str = "off"
    # Fabric-observatory sampling grid in simulated ns (the same
    # grid-crossing rule as netstat_interval).  0 = every round.
    fabricstat_interval_ns: int = 0
    # Top-N cap shared by every Chrome per-entity counter-track
    # family (per-connection sim-netstat tracks, per-process syscall
    # tracks, per-link fabric tracks): exports stay loadable at 10k
    # hosts.  Was hard-coded per exporter.
    chrome_top_n: int = 16
    # Syscall observatory (docs/OBSERVABILITY.md "syscall
    # observatory"): "on" records the deterministic per-syscall
    # sim-time channel (syscalls-sim.bin: one fixed record per
    # managed-process syscall dispatch, byte-identical across runs and
    # schedulers) AND the wall-time IPC round-trip profile
    # (metrics.wall.ipc.*); "wall" records the wall profile only —
    # what bench's managed rung uses.  The SC_* disposition counters
    # (metrics.sim.syscalls.dispositions) run regardless — cheap
    # integer adds, like drop attribution.
    syscall_observatory: str = "off"
    # Device-kernel observatory (docs/OBSERVABILITY.md "Device-kernel
    # observatory"): "on" records the FIFTH deterministic sim-time
    # channel (kernel-sim.bin: one KS_REC per committed device span —
    # per-micro-op-stage fire counts and active-lane sums threaded
    # through both span kernels' while_loop carries; occupancy =
    # lanes / (hosts x trips), trips reconcile exactly against the
    # dispatch split's micro_iters) AND the wall-side dispatch
    # attribution; "wall" records the wall side only: explicit
    # _FN_CACHE hit/miss/build-wall accounting, per-kernel
    # Compiled.cost_analysis() flops/bytes via the AOT dispatch path,
    # export/import codec byte volume and the speculative-window
    # rollback ledger (metrics.wall.dispatch.*).  "off" records
    # neither; the fn_cache/rollback counters still accumulate (cheap
    # integer adds) and surface in metrics.wall.dispatch.
    kernel_observatory: str = "off"
    # Syscall service plane (docs/OBSERVABILITY.md "Syscall service
    # plane", ROADMAP item 2): per conservative round, every managed
    # host's due servicing work is drained by a host-affine worker
    # pool instead of the scheduler's serial host walk — each host
    # stays on one worker group so per-host event order (and the
    # byte-identical syscalls-sim.bin channel) is preserved, while
    # the futex waits of independent hosts' round trips overlap.
    # "auto" enables it whenever managed (real-binary) processes are
    # configured and more than one worker is available; "on" forces
    # it; "off" keeps the scheduler's own host walk.  Byte identity
    # holds in every mode (gated in tests/test_svc.py).
    syscall_service_plane: str = "auto"
    # Channel-wait slice between waitpid safety-net polls while a
    # managed thread blocks in its IPC recv.  Child death is normally
    # detected by the ChildWatcher closing the IPC block; this poll is
    # only the fallback, so it can be long without costing latency.
    # Wall-side only (never reaches simulation bytes); the effective
    # value is surfaced in metrics.wall.ipc.death_poll_ns.
    managed_death_poll_ns: int = 2_000_000_000
    # Wall-time hang watchdog for managed processes
    # (docs/ROBUSTNESS.md): a managed thread that produces no IPC
    # event for this much WALL time while its native process is still
    # alive (e.g. spinning in userspace without syscalls) is treated
    # as hung — the native process is SIGKILLed and the process's
    # on_failure containment policy engages at the deterministic sim
    # instant the host was servicing.  0 disables (the default: a
    # parked-on-condition process is NOT hung, and the watchdog only
    # guards the raw IPC recv).  Wall-only, digest-skipped.
    managed_watchdog_ns: int = 0
    # Spawn-storm taming (ROADMAP item 2): minimum WALL-time gap
    # between successive managed posix_spawns.  A 10k-binary fleet
    # spawning in one round thrashes the kernel (fork+LD_BIND_NOW
    # relocation storms); staggering trades a little wall latency for
    # a stable spawn rate.  0 disables.  Wall-only, digest-skipped —
    # simulation bytes are identical at any stagger.
    managed_spawn_stagger_ns: int = 0
    # Max conservative rounds a C++ engine span may buffer between
    # pcap drains when engine-side capture is active (was hard-coded;
    # per-round streams must not buffer a whole sim).  The effective
    # value is recorded in metrics.wall.dispatch.pcap_span_cap.
    pcap_span_cap: int = 64
    # DCTCP instantaneous marking threshold K (RFC 8257 4.1), the
    # sweep subsystem's primary congestion-control axis
    # (docs/SWEEP.md): an ECT(0) packet arriving while the router
    # queue already holds >= dctcp_k_pkts packets — or >= dctcp_k_bytes
    # bytes — is rewritten CE.  Defaults are the net/codel.py /
    # netplane.cpp twin constants (20 pkts / 30000 B); the knob is
    # SIMULATION-SEMANTIC (in the checkpoint config digest) but
    # fork-safe (tools/ckpt fork may rewrite it: K shapes future
    # marking only, never the meaning of snapshotted state).
    dctcp_k_pkts: int = 20
    dctcp_k_bytes: int = 30_000
    # Pin worker threads to distinct CPUs (ref: affinity.c, on by
    # default; docs/parallel_sims.md reports ~3x cost when off).
    use_cpu_pinning: bool = True
    # Opt-in crypto no-op preload for managed processes (ref:
    # preload-openssl/crypto.c, the Tor-sim perf hack): AES/ctr128
    # symmetric-cipher work becomes an identity transform.  Breaks real
    # crypto correctness by design; off unless a sim explicitly trades
    # fidelity for wall time.
    openssl_crypto_noop: bool = False
    # perf_timers cargo-feature equivalent: per-host execution wall time
    # in sim-stats.json (ref: utility/perf_timer.rs).
    use_perf_timers: bool = False
    report_errors_to_stderr: bool = True


def _ns(v: int | None):
    return None if v is None else f"{int(v)} ns"


@dataclass
class ConfigOptions:
    general: GeneralConfig
    network: NetworkConfig
    experimental: ExperimentalConfig
    hosts: dict[str, HostConfig]
    checkpoint: CheckpointConfig | None = None
    faults: list[FaultConfig] = field(default_factory=list)

    def to_processed_dict(self) -> dict:
        """The fully-resolved options as a re-loadable YAML structure —
        written into the data dir for reproducibility (ref:
        manager.rs:183-194 re-serializes the processed config the same
        way).  Every value is explicit, defaults included; time values
        render as '<n> ns' so from_yaml_text() round-trips."""
        g, e = self.general, self.experimental
        out = {
            "general": {
                "stop_time": _ns(g.stop_time_ns),
                "seed": g.seed,
                "bootstrap_end_time": _ns(g.bootstrap_end_time_ns),
                "parallelism": g.parallelism,
                "data_directory": g.data_directory,
                "template_directory": g.template_directory,
                "progress": g.progress,
                "heartbeat_interval": _ns(g.heartbeat_interval_ns),
                "log_level": g.log_level,
                "model_unblocked_syscall_latency":
                    g.model_unblocked_syscall_latency,
            },
            "network": {
                "graph": {"type": "gml",
                          "inline": self.network.graph.gml_text},
                "use_shortest_path": self.network.use_shortest_path,
            },
            "experimental": {
                "scheduler": e.scheduler,
                "runahead": _ns(e.runahead_ns),
                "use_dynamic_runahead": e.use_dynamic_runahead,
                "interface_qdisc": e.interface_qdisc,
                "socket_send_buffer": e.socket_send_buffer,
                "socket_recv_buffer": e.socket_recv_buffer,
                "socket_send_autotune": e.socket_send_autotune,
                "socket_recv_autotune": e.socket_recv_autotune,
                "strace_logging_mode": e.strace_logging_mode,
                "max_unapplied_cpu_latency":
                    _ns(e.max_unapplied_cpu_latency_ns),
                "unblocked_syscall_latency":
                    _ns(e.unblocked_syscall_latency_ns),
                "unblocked_vdso_latency": _ns(e.unblocked_vdso_latency_ns),
                "host_cpu_threshold": _ns(e.host_cpu_threshold_ns),
                "host_cpu_precision": _ns(e.host_cpu_precision_ns),
                "host_cpu_event_cost": _ns(e.host_cpu_event_cost_ns),
                "native_preemption_enabled": e.native_preemption_enabled,
                "native_preemption_native_interval":
                    _ns(e.native_preemption_native_interval_ns),
                "native_preemption_sim_interval":
                    _ns(e.native_preemption_sim_interval_ns),
                "native_file_io_bandwidth":
                    f"{e.native_file_io_bandwidth_bps} B",
                "tpu_max_packets_per_round": e.tpu_max_packets_per_round,
                "tpu_min_device_batch": e.tpu_min_device_batch,
                "tpu_shards": e.tpu_shards,
                "tpu_exchange_capacity": e.tpu_exchange_capacity,
                "native_dataplane": e.native_dataplane,
                "tpu_device_spans": e.tpu_device_spans,
                "tpu_donate_buffers": e.tpu_donate_buffers,
                "span_overlap": e.span_overlap,
                "pallas_queue_kernels": e.pallas_queue_kernels,
                "dev_span_k_init": e.dev_span_k_init,
                "dev_span_k_floor": e.dev_span_k_floor,
                "dev_span_k_shrink": e.dev_span_k_shrink,
                "flight_recorder": e.flight_recorder,
                "sim_netstat": e.sim_netstat,
                "netstat_interval": _ns(e.netstat_interval_ns),
                "sim_fabricstat": e.sim_fabricstat,
                "fabricstat_interval": _ns(e.fabricstat_interval_ns),
                "chrome_top_n": e.chrome_top_n,
                "syscall_observatory": e.syscall_observatory,
                "kernel_observatory": e.kernel_observatory,
                "syscall_service_plane": e.syscall_service_plane,
                "managed_death_poll": _ns(e.managed_death_poll_ns),
                "managed_watchdog": _ns(e.managed_watchdog_ns),
                "managed_spawn_stagger": _ns(e.managed_spawn_stagger_ns),
                "pcap_span_cap": e.pcap_span_cap,
                "dctcp_k_pkts": e.dctcp_k_pkts,
                "dctcp_k_bytes": e.dctcp_k_bytes,
                "openssl_crypto_noop": e.openssl_crypto_noop,
                "use_cpu_pinning": e.use_cpu_pinning,
                "use_perf_timers": e.use_perf_timers,
                "report_errors_to_stderr": e.report_errors_to_stderr,
            },
            "hosts": {},
        }
        if self.checkpoint is not None:
            out["checkpoint"] = {
                "at": [_ns(t) for t in self.checkpoint.at_ns],
                "directory": self.checkpoint.directory,
            }
        if self.faults:
            out["faults"] = [{
                "at": _ns(f.at_ns),
                "action": f.action,
                "host": f.host,
                "snapshot": f.snapshot,
            } for f in self.faults]
        for name in sorted(self.hosts):
            h = self.hosts[name]
            procs = []
            for p in h.processes:
                procs.append({
                    "path": p.path,
                    "args": list(p.args),
                    "environment": dict(p.environment),
                    "start_time": _ns(p.start_time_ns),
                    "shutdown_time": _ns(p.shutdown_time_ns),
                    "shutdown_signal": p.shutdown_signal,
                    "expected_final_state": p.expected_final_state,
                    "on_failure": p.on_failure,
                    "restart_budget": p.restart_budget,
                })
            out["hosts"][name] = {
                "network_node_id": h.network_node_id,
                "ip_addr": (netgraph.format_ip(h.ip_addr)
                            if h.ip_addr is not None else None),
                "bandwidth_down": h.bandwidth_down_bits,
                "bandwidth_up": h.bandwidth_up_bits,
                "pcap_enabled": h.pcap_enabled,
                "pcap_capture_size": h.pcap_capture_size,
                "native_dataplane": h.native_dataplane,
                "tcp": {"cc": h.tcp_cc,
                        "ecn": "on" if h.tcp_ecn else "off"},
                "processes": procs,
            }

        def prune(x):
            # Omit None values: absent and null are not equivalent to
            # the loader (e.g. shutdown_time's presence check).
            if isinstance(x, dict):
                return {k: prune(v) for k, v in x.items()
                        if v is not None}
            if isinstance(x, list):
                return [prune(v) for v in x]
            return x

        return prune(out)

    @classmethod
    def from_yaml_text(cls, text: str, base_dir: str = ".") -> "ConfigOptions":
        raw = yaml.safe_load(text)
        if not isinstance(raw, dict):
            raise ValueError("config root must be a mapping")
        return cls.from_dict(raw, base_dir=base_dir)

    @classmethod
    def from_file(cls, path: str) -> "ConfigOptions":
        import os
        with open(path) as f:
            return cls.from_yaml_text(f.read(), base_dir=os.path.dirname(path) or ".")

    @classmethod
    def from_dict(cls, raw: dict, base_dir: str = ".") -> "ConfigOptions":
        raw = {k: v for k, v in raw.items() if not str(k).startswith("x-")}
        unknown = set(raw) - {"general", "network", "experimental",
                              "hosts", "host_option_defaults",
                              "checkpoint", "faults"}
        if unknown:
            raise ValueError(f"unknown config sections: {sorted(unknown)}")

        g = raw.get("general", {}) or {}
        general = GeneralConfig(
            stop_time_ns=units.parse_time_ns(_require(g, "stop_time", "general")),
            seed=int(g.get("seed", 1)),
            bootstrap_end_time_ns=units.parse_time_ns(g.get("bootstrap_end_time", 0)),
            parallelism=int(g.get("parallelism", 0)),
            data_directory=str(g.get("data_directory", "shadow.data")),
            template_directory=g.get("template_directory"),
            progress=bool(g.get("progress", False)),
            heartbeat_interval_ns=units.parse_time_ns(g.get("heartbeat_interval", "1 s")),
            log_level=str(g.get("log_level", "info")),
            model_unblocked_syscall_latency=bool(
                g.get("model_unblocked_syscall_latency", True)),
        )

        n = raw.get("network", {}) or {}
        gspec = _require(n, "graph", "network")
        network = NetworkConfig(
            graph=_load_graph(gspec, base_dir),
            use_shortest_path=bool(n.get("use_shortest_path", True)),
        )

        e = raw.get("experimental", {}) or {}
        experimental = ExperimentalConfig()
        for yaml_key, attr, conv in (
                ("scheduler", "scheduler", str),
                ("runahead", "runahead_ns", units.parse_time_ns),
                ("use_dynamic_runahead", "use_dynamic_runahead", bool),
                ("interface_qdisc", "interface_qdisc", str),
                ("socket_send_buffer", "socket_send_buffer", units.parse_bytes),
                ("socket_recv_buffer", "socket_recv_buffer", units.parse_bytes),
                ("socket_send_autotune", "socket_send_autotune", bool),
                ("socket_recv_autotune", "socket_recv_autotune", bool),
                ("strace_logging_mode", "strace_logging_mode", str),
                ("max_unapplied_cpu_latency", "max_unapplied_cpu_latency_ns",
                 units.parse_time_ns),
                ("unblocked_syscall_latency", "unblocked_syscall_latency_ns",
                 units.parse_time_ns),
                ("unblocked_vdso_latency", "unblocked_vdso_latency_ns",
                 units.parse_time_ns),
                ("host_cpu_threshold", "host_cpu_threshold_ns",
                 units.parse_time_ns),
                ("host_cpu_precision", "host_cpu_precision_ns",
                 units.parse_time_ns),
                ("host_cpu_event_cost", "host_cpu_event_cost_ns",
                 units.parse_time_ns),
                ("native_preemption_enabled", "native_preemption_enabled",
                 bool),
                ("native_preemption_native_interval",
                 "native_preemption_native_interval_ns",
                 units.parse_time_ns),
                ("native_preemption_sim_interval",
                 "native_preemption_sim_interval_ns",
                 units.parse_time_ns),
                ("native_file_io_bandwidth", "native_file_io_bandwidth_bps",
                 units.parse_bytes),
                ("tpu_max_packets_per_round", "tpu_max_packets_per_round", int),
                ("tpu_min_device_batch", "tpu_min_device_batch", int),
                ("tpu_shards", "tpu_shards", int),
                ("tpu_exchange_capacity", "tpu_exchange_capacity", int),
                # YAML 1.1 reads bare on/off as booleans; accept both
                # spellings (`native_dataplane: on` is the documented
                # form).
                ("native_dataplane", "native_dataplane",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("tpu_device_spans", "tpu_device_spans",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("tpu_donate_buffers", "tpu_donate_buffers",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("span_overlap", "span_overlap",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("pallas_queue_kernels", "pallas_queue_kernels",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("dev_span_k_init", "dev_span_k_init", int),
                ("dev_span_k_floor", "dev_span_k_floor", int),
                ("dev_span_k_shrink", "dev_span_k_shrink", int),
                ("flight_recorder", "flight_recorder",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("sim_netstat", "sim_netstat",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("netstat_interval", "netstat_interval_ns",
                 units.parse_time_ns),
                ("sim_fabricstat", "sim_fabricstat",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("fabricstat_interval", "fabricstat_interval_ns",
                 units.parse_time_ns),
                ("chrome_top_n", "chrome_top_n", int),
                ("syscall_observatory", "syscall_observatory",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("kernel_observatory", "kernel_observatory",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("syscall_service_plane", "syscall_service_plane",
                 lambda v: ("on" if v else "off") if isinstance(v, bool)
                 else str(v)),
                ("managed_death_poll", "managed_death_poll_ns",
                 units.parse_time_ns),
                ("managed_watchdog", "managed_watchdog_ns",
                 units.parse_time_ns),
                ("managed_spawn_stagger", "managed_spawn_stagger_ns",
                 units.parse_time_ns),
                ("pcap_span_cap", "pcap_span_cap", int),
                ("dctcp_k_pkts", "dctcp_k_pkts", int),
                ("dctcp_k_bytes", "dctcp_k_bytes", units.parse_bytes),
                ("use_cpu_pinning", "use_cpu_pinning", bool),
                ("openssl_crypto_noop", "openssl_crypto_noop", bool),
                ("use_perf_timers", "use_perf_timers", bool),
                ("report_errors_to_stderr", "report_errors_to_stderr", bool)):
            if yaml_key in e:
                setattr(experimental, attr, conv(e[yaml_key]))
        if experimental.scheduler not in SCHEDULERS:
            raise ValueError(f"unknown scheduler {experimental.scheduler!r}; "
                             f"expected one of {SCHEDULERS}")
        if experimental.interface_qdisc not in QDISC_MODES:
            raise ValueError(f"unknown interface_qdisc "
                             f"{experimental.interface_qdisc!r}")
        if experimental.flight_recorder not in ("off", "wall", "on"):
            raise ValueError(
                f"unknown flight_recorder "
                f"{experimental.flight_recorder!r}; expected one of "
                f"('off', 'wall', 'on')")
        if experimental.sim_netstat not in ("off", "on"):
            raise ValueError(
                f"unknown sim_netstat {experimental.sim_netstat!r}; "
                f"expected one of ('off', 'on')")
        if experimental.sim_fabricstat not in ("off", "on"):
            raise ValueError(
                f"unknown sim_fabricstat "
                f"{experimental.sim_fabricstat!r}; "
                f"expected one of ('off', 'on')")
        if experimental.chrome_top_n < 1:
            raise ValueError("chrome_top_n must be >= 1")
        if experimental.syscall_observatory not in ("off", "wall", "on"):
            raise ValueError(
                f"unknown syscall_observatory "
                f"{experimental.syscall_observatory!r}; expected one of "
                f"('off', 'wall', 'on')")
        if experimental.kernel_observatory not in ("off", "wall", "on"):
            raise ValueError(
                f"unknown kernel_observatory "
                f"{experimental.kernel_observatory!r}; expected one of "
                f"('off', 'wall', 'on')")
        if experimental.syscall_service_plane not in ("off", "auto",
                                                      "on"):
            raise ValueError(
                f"unknown syscall_service_plane "
                f"{experimental.syscall_service_plane!r}; expected one "
                f"of ('off', 'auto', 'on')")
        if experimental.managed_death_poll_ns < 1_000_000:
            raise ValueError(
                "managed_death_poll must be >= 1ms (it is the waitpid "
                "safety-net poll slice, not a latency knob)")
        if experimental.managed_watchdog_ns < 0 or \
                0 < experimental.managed_watchdog_ns < 100_000_000:
            raise ValueError(
                "managed_watchdog must be 0 (off) or >= 100ms — a "
                "shorter wall watchdog would kill healthy processes "
                "mid-compute")
        if experimental.managed_spawn_stagger_ns < 0:
            raise ValueError("managed_spawn_stagger must be >= 0")
        if experimental.pcap_span_cap < 1:
            raise ValueError("pcap_span_cap must be >= 1")
        if experimental.dctcp_k_pkts < 1:
            raise ValueError("dctcp_k_pkts must be >= 1")
        if experimental.dctcp_k_bytes < 1:
            raise ValueError("dctcp_k_bytes must be >= 1")
        if experimental.tpu_donate_buffers not in ("off", "on"):
            raise ValueError(
                f"unknown tpu_donate_buffers "
                f"{experimental.tpu_donate_buffers!r}; "
                f"expected one of ('off', 'on')")
        if experimental.span_overlap not in ("off", "on", "auto"):
            raise ValueError(
                f"unknown span_overlap "
                f"{experimental.span_overlap!r}; "
                f"expected one of ('off', 'on', 'auto')")
        if experimental.pallas_queue_kernels not in ("off", "on"):
            raise ValueError(
                f"unknown pallas_queue_kernels "
                f"{experimental.pallas_queue_kernels!r}; "
                f"expected one of ('off', 'on')")
        if experimental.dev_span_k_init < 1:
            raise ValueError("dev_span_k_init must be >= 1")
        if experimental.dev_span_k_floor < 1:
            raise ValueError("dev_span_k_floor must be >= 1")
        if experimental.dev_span_k_shrink < 1:
            raise ValueError("dev_span_k_shrink must be >= 1")

        hosts_raw = raw.get("hosts", {}) or {}
        if not hosts_raw:
            raise ValueError("config must define at least one host")
        # host_option_defaults (configuration.rs:594 HostDefaultOptions):
        # simulation-wide defaults each host may override in its own
        # host_options block.  Only implemented options are accepted —
        # a typo'd or unsupported key must fail, not silently no-op.
        _HOST_OPTION_KEYS = {"pcap_enabled", "pcap_capture_size",
                             "native_dataplane", "tcp"}

        def _host_options(section: str, d: dict) -> dict:
            unknown = set(d) - _HOST_OPTION_KEYS
            if unknown:
                raise ValueError(f"{section}: unsupported option(s) "
                                 f"{sorted(unknown)}")
            return d

        def _tcp_block(section: str, d) -> tuple[str, bool]:
            """One `tcp:` block -> (cc, ecn).  YAML 1.1 reads bare
            on/off as booleans, so both spellings are accepted."""
            if not isinstance(d, dict):
                raise ValueError(f"{section}.tcp: must be a mapping")
            unknown = set(d) - {"cc", "ecn"}
            if unknown:
                raise ValueError(f"{section}.tcp: unknown key(s) "
                                 f"{sorted(unknown)}")
            cc = str(d.get("cc", "reno"))
            if cc not in ("reno", "dctcp"):
                raise ValueError(f"{section}.tcp.cc: expected one of "
                                 f"('reno', 'dctcp'), got {cc!r}")
            ecn = d.get("ecn", False)
            if isinstance(ecn, str):
                if ecn not in ("on", "off"):
                    raise ValueError(f"{section}.tcp.ecn: expected "
                                     f"'on' or 'off', got {ecn!r}")
                ecn = ecn == "on"
            ecn = bool(ecn)
            if cc == "dctcp" and not ecn:
                raise ValueError(
                    f"{section}.tcp: cc=dctcp requires ecn=on (without "
                    f"an echo the controller degenerates to reno)")
            return cc, ecn

        defaults_raw = _host_options(
            "host_option_defaults",
            raw.get("host_option_defaults", {}) or {})
        if "tcp" in defaults_raw:
            # Validate the default block eagerly with its own section
            # label — a bad default must fail loudly even when every
            # host overrides it.
            _tcp_block("host_option_defaults", defaults_raw["tcp"])

        hosts = {}
        for name, h in hosts_raw.items():
            h = h or {}
            opt = dict(defaults_raw)
            opt.update(_host_options(f"hosts.{name}.host_options",
                                     h.get("host_options", {}) or {}))
            procs = []
            for p in h.get("processes", []) or []:
                args = p.get("args", [])
                if isinstance(args, str):
                    args = shlex.split(args)
                on_failure = str(p.get("on_failure", "abort"))
                if on_failure not in ON_FAILURE_POLICIES:
                    raise ValueError(
                        f"hosts.{name}.processes[{len(procs)}]: unknown "
                        f"on_failure {on_failure!r}; expected one of "
                        f"{ON_FAILURE_POLICIES}")
                restart_budget = int(p.get("restart_budget", 2))
                if restart_budget < 1:
                    raise ValueError(
                        f"hosts.{name}.processes[{len(procs)}]: "
                        f"restart_budget must be >= 1")
                procs.append(ProcessConfig(
                    path=str(_require(p, "path", f"hosts.{name}.processes")),
                    args=[str(a) for a in args],
                    environment={str(k): str(v) for k, v in
                                 (p.get("environment") or {}).items()},
                    start_time_ns=units.parse_time_ns(p.get("start_time", 0)),
                    shutdown_time_ns=(units.parse_time_ns(p["shutdown_time"])
                                      if "shutdown_time" in p else None),
                    shutdown_signal=str(p.get("shutdown_signal", "SIGTERM")),
                    expected_final_state=_validate_final_state(
                        p.get("expected_final_state", "exited 0"),
                        f"hosts.{name}.processes[{len(procs)}]"),
                    on_failure=on_failure,
                    restart_budget=restart_budget,
                ))
            bw_down = h.get("bandwidth_down")
            bw_up = h.get("bandwidth_up")
            tcp_raw = h.get("tcp", opt.get("tcp"))
            tcp_cc, tcp_ecn = (("reno", False) if tcp_raw is None
                               else _tcp_block(f"hosts.{name}", tcp_raw))
            hosts[str(name)] = HostConfig(
                name=str(name),
                network_node_id=int(_require(h, "network_node_id",
                                             f"hosts.{name}")),
                processes=procs,
                ip_addr=(netgraph.parse_ip(h["ip_addr"])
                         if "ip_addr" in h else None),
                bandwidth_down_bits=(units.parse_bandwidth_bits(bw_down)
                                     if bw_down is not None else None),
                bandwidth_up_bits=(units.parse_bandwidth_bits(bw_up)
                                   if bw_up is not None else None),
                pcap_enabled=bool(h.get("pcap_enabled",
                                        opt.get("pcap_enabled", False))),
                pcap_capture_size=units.parse_bytes(
                    h.get("pcap_capture_size",
                          opt.get("pcap_capture_size", 65535))),
                native_dataplane=bool(
                    h.get("native_dataplane",
                          opt.get("native_dataplane", True))),
                tcp_cc=tcp_cc,
                tcp_ecn=tcp_ecn,
            )
        checkpoint = None
        ck_raw = raw.get("checkpoint")
        if ck_raw is not None:
            if not isinstance(ck_raw, dict):
                raise ValueError("checkpoint: must be a mapping")
            ck_unknown = set(ck_raw) - {"at", "directory"}
            if ck_unknown:
                raise ValueError(f"checkpoint: unknown key(s) "
                                 f"{sorted(ck_unknown)}")
            ats = ck_raw.get("at", [])
            if not isinstance(ats, list):
                ats = [ats]
            checkpoint = CheckpointConfig(
                at_ns=sorted(units.parse_time_ns(t) for t in ats),
                directory=(str(ck_raw["directory"])
                           if ck_raw.get("directory") is not None
                           else None))

        faults: list[FaultConfig] = []
        for i, f in enumerate(raw.get("faults") or []):
            if not isinstance(f, dict):
                raise ValueError(f"faults[{i}]: must be a mapping")
            f_unknown = set(f) - {"at", "action", "host", "snapshot"}
            if f_unknown:
                raise ValueError(f"faults[{i}]: unknown key(s) "
                                 f"{sorted(f_unknown)}")
            action = str(_require(f, "action", f"faults[{i}]"))
            if action not in FAULT_ACTIONS:
                raise ValueError(f"faults[{i}]: unknown action "
                                 f"{action!r}; expected one of "
                                 f"{FAULT_ACTIONS}")
            host = str(_require(f, "host", f"faults[{i}]"))
            if host not in hosts:
                raise ValueError(f"faults[{i}]: unknown host {host!r}")
            snapshot = f.get("snapshot")
            if action == "host_restore" and not snapshot:
                raise ValueError(f"faults[{i}]: host_restore needs a "
                                 f"`snapshot` archive path")
            faults.append(FaultConfig(
                at_ns=units.parse_time_ns(_require(f, "at",
                                                   f"faults[{i}]")),
                action=action, host=host,
                snapshot=str(snapshot) if snapshot else None))
        # Deterministic application order: (time, config index) — the
        # manager's choke point pops them in this order.
        faults.sort(key=lambda fc: fc.at_ns)

        return cls(general=general, network=network,
                   experimental=experimental, hosts=hosts,
                   checkpoint=checkpoint, faults=faults)


def _require(mapping: dict, key: str, where: str):
    if key not in mapping:
        raise ValueError(f"missing required config key {where}.{key}")
    return mapping[key]


def _validate_final_state(v, where: str):
    """Fail loudly on malformed expected_final_state (a typo would
    otherwise change run outcomes — and could do so differently per
    backend)."""
    if isinstance(v, str):
        if v in ("running", "any"):
            return v
        parts = v.split()
        try:
            if parts and parts[0] == "exited" and len(parts) <= 2:
                if len(parts) == 2:
                    int(parts[1])
                return v
            if parts and parts[0] == "signaled" and len(parts) <= 2:
                if len(parts) == 2:
                    from shadow_tpu.host.signals import (NSIG,
                                                         parse_signal)
                    sig = parse_signal(parts[1])
                    if not 0 < sig < NSIG:
                        raise ValueError(f"signal {sig} out of range")
                return v
        except ValueError:
            pass
    raise ValueError(
        f"{where}: invalid expected_final_state {v!r} (expected "
        f"'running', 'any', 'exited [code]', or 'signaled [SIG]')")


def _load_graph(gspec: dict, base_dir: str) -> netgraph.NetworkGraph:
    gtype = gspec.get("type", "gml")
    if gtype in netgraph.BUILTIN_GRAPHS:
        return netgraph.NetworkGraph.named(gtype)
    if gtype != "gml":
        raise ValueError(f"unknown graph type {gtype!r}")
    if "inline" in gspec:
        return netgraph.NetworkGraph.from_gml(gspec["inline"])
    if "file" in gspec:
        import os
        path = gspec["file"]["path"]
        if not os.path.isabs(path):
            path = os.path.join(base_dir, path)
        with open(path) as f:
            return netgraph.NetworkGraph.from_gml(f.read())
    raise ValueError("network.graph needs 'inline' or 'file.path'")
