"""Learned fabric surrogate (docs/SWEEP.md "Surrogate").

A small pure-JAX message-passing GNN in the RouteNet shape
(arXiv 1910.01508): link-state and flow-state embeddings coupled along
flow paths derived from each campaign point's topology, trained on
sweep datasets to predict per-flow FCT and per-link peak queue depth,
validated against held-out simulated fabrics.

- features.py — dataset -> per-point graph samples (paths via
  deterministic Dijkstra over the recorded topology)
- model.py    — the GNN: counter-based threefry init, forward pass
- train.py    — hand-rolled Adam loop, held-out split, the
  surrogate-vs-simulator per-quantile error table
"""
