"""Surrogate training + held-out-fabric validation.

Hand-rolled Adam (no optimizer dependency) over the summed per-point
loss: flow-FCT MSE in log10 space plus the masked per-link
peak-queue MSE.  Everything is deterministic: threefry init, fixed
sample order (the dataset's matrix order), full-batch gradients.

The VALIDATION PROTOCOL is held-out-fabric (docs/SWEEP.md): the
holdout predicate names a feature and a threshold — e.g.
("fan_in", 16) trains on every point with fan_in < 16 and evaluates
on fan_in >= 16; ("n_leaf", 16) is the leaf-spine size split.  The
error table reports, per held-out point, the relative error of the
PREDICTED FCT quantiles against the simulator's (quantiles taken
over each point's flow population — the tail numbers the sweep
exists to measure), plus the peak-queue relative error.  Honest by
construction: the table is computed fresh from the held-out samples
every time and recorded even when the errors are embarrassing.
"""

from __future__ import annotations

import math

import numpy as np

from shadow_tpu.surrogate import model as model_mod
from shadow_tpu.trace.fabricstat import percentile

QUANTILES = (("p50", 500), ("p99", 990), ("p999", 999))


def split_samples(samples: list, holdout_feature: str,
                  holdout_min) -> tuple[list, list]:
    """(train, held_out): a sample is held out iff its point's
    `holdout_feature` is >= holdout_min (equality included — the
    held-out fabric is never trained on).  Only NUMERIC features
    split; a string feature (cc, scenario, size_law) is refused with
    the valid names listed."""
    train, held = [], []
    for s in samples:
        v = s["features"].get(holdout_feature, 0)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            numeric = sorted(k for k, fv in s["features"].items()
                             if isinstance(fv, (int, float))
                             and not isinstance(fv, bool))
            raise ValueError(
                f"holdout feature {holdout_feature!r} is not "
                f"numeric (value {v!r}); numeric features: "
                f"{numeric}")
        (held if v >= holdout_min else train).append(s)
    return train, held


ARRAY_KEYS = ("link_feats", "flow_feats", "pairs", "flow_t",
              "link_t", "link_mask")


def _arrays(sample: dict) -> dict:
    """The jit-traceable slice of a sample (the id/feature strings
    stay outside the traced pytree)."""
    return {k: sample[k] for k in ARRAY_KEYS}


def _loss_fn(params, arrs):
    import jax.numpy as jnp
    flow_pred, link_pred = model_mod.forward(params, arrs)
    fl = jnp.mean((flow_pred - jnp.asarray(arrs["flow_t"])) ** 2)
    mask = jnp.asarray(arrs["link_mask"])
    ll = jnp.sum(mask * (link_pred
                         - jnp.asarray(arrs["link_t"])) ** 2) \
        / jnp.maximum(mask.sum(), 1.0)
    return fl + 0.5 * ll


def train(samples: list, seed: int = 1, steps: int = 300,
          lr: float = 3e-3,
          log=None) -> tuple[dict, list]:
    """Adam over the summed per-sample loss.  Returns (params,
    loss_history) — the history is what the loss-decreases smoke
    gate asserts on."""
    import jax
    import jax.numpy as jnp

    if not samples:
        raise ValueError("no training samples (is the holdout "
                         "predicate eating the whole campaign?)")
    params = jax.tree_util.tree_map(jnp.asarray,
                                    model_mod.init_params(seed))
    grad_fn = jax.jit(jax.value_and_grad(_loss_fn))
    m = jax.tree_util.tree_map(jnp.zeros_like, params)
    v = jax.tree_util.tree_map(jnp.zeros_like, params)
    b1, b2, eps = 0.9, 0.999, 1e-8
    history = []
    for step in range(1, steps + 1):
        total = 0.0
        grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        for s in samples:
            loss, g = grad_fn(params, _arrays(s))
            total += float(loss)
            grads = jax.tree_util.tree_map(jnp.add, grads, g)
        m = jax.tree_util.tree_map(
            lambda mm, gg: b1 * mm + (1 - b1) * gg, m, grads)
        v = jax.tree_util.tree_map(
            lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, v, grads)
        scale = lr * math.sqrt(1 - b2 ** step) / (1 - b1 ** step)
        params = jax.tree_util.tree_map(
            lambda p, mm, vv: p - scale * mm / (jnp.sqrt(vv) + eps),
            params, m, v)
        history.append(total / len(samples))
        if log is not None and (step % 50 == 0 or step == 1):
            log(f"surrogate: step {step:>4} loss {history[-1]:.4f}")
    params = jax.tree_util.tree_map(np.asarray, params)
    return params, history


def predict(params: dict, sample: dict):
    """(flow FCT ns predictions, per-link peak-depth predictions) in
    LINEAR units."""
    flow_pred, link_pred = model_mod.forward(params, sample)
    fct_ns = np.power(10.0, np.asarray(flow_pred)).astype(np.float64)
    peak = np.power(10.0, np.asarray(link_pred)) - 1.0
    return fct_ns, np.maximum(peak, 0.0)


def error_table(params: dict, held_out: list) -> dict:
    """The surrogate-vs-simulator table `bench[sweep-*]` records: per
    held-out point, relative error of each predicted FCT quantile
    (over the point's flows) and of the predicted peak queue depth;
    plus the mean absolute relative error per quantile."""
    rows = []
    for s in held_out:
        pred_ns, pred_peak = predict(params, s)
        sim_ns = np.power(10.0, s["flow_t"].astype(np.float64))
        row = {"point_id": s["point_id"],
               "flows": int(len(sim_ns))}
        for name, permille in QUANTILES:
            sim_q = percentile(sorted(sim_ns.tolist()), permille)
            pred_q = percentile(sorted(pred_ns.tolist()), permille)
            row[f"sim_{name}_ns"] = int(sim_q)
            row[f"pred_{name}_ns"] = int(pred_q)
            row[f"rel_err_{name}"] = round(
                abs(pred_q - sim_q) / max(sim_q, 1), 4)
        mask = s["link_mask"] > 0
        if mask.any():
            sim_peak = float(np.max(
                np.power(10.0, s["link_t"][mask]) - 1.0))
            pk = float(np.max(pred_peak[mask]))
            row["sim_peak_queue"] = round(sim_peak, 1)
            row["pred_peak_queue"] = round(pk, 1)
            row["rel_err_peak"] = round(
                abs(pk - sim_peak) / max(sim_peak, 1.0), 4)
        rows.append(row)
    out = {"points": rows}
    for name, _p in QUANTILES:
        errs = [r[f"rel_err_{name}"] for r in rows]
        out[f"mean_rel_err_{name}"] = (round(sum(errs) / len(errs), 4)
                                       if errs else None)
    return out
