"""The fabric surrogate: a small pure-JAX message-passing GNN in the
RouteNet shape (arXiv 1910.01508).

Two entity sets — links and flows — carry hidden states.  T rounds of
coupled updates: every flow aggregates the states of the links on its
path and updates; every link aggregates the states of the flows
crossing it and updates.  Readout MLPs map the final states to
per-flow log10 FCT and per-link log10(1 + peak queue depth).

Initialization is COUNTER-BASED threefry (core/rng.py — the repo's
one RNG), each parameter tensor filled from its own counter range, so
`init_params(seed)` is bit-reproducible with no global RNG state and
training runs are deterministic end to end.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.core.rng import (STREAM_SURROGATE, mix_key,
                                 threefry2x32_np)

HIDDEN = 32
T_STEPS = 4
LINK_IN = 3   # features.py link_feats width
FLOW_IN = 6   # features.py flow_feats width


def _fill(seed: int, tensor_idx: int, shape: tuple,
          scale: float) -> np.ndarray:
    """Deterministic uniform(-scale, scale) tensor from threefry
    counters (tensor_idx, element_idx) — order-free, so two inits of
    the same seed agree bit-for-bit."""
    n = int(np.prod(shape))
    k0, k1 = mix_key(seed, STREAM_SURROGATE)
    b0, _b1 = threefry2x32_np(
        np.uint32(k0), np.uint32(k1),
        np.full(n, tensor_idx, np.uint32),
        np.arange(n, dtype=np.uint32))
    u = b0.astype(np.float64) / float(1 << 32)  # [0, 1)
    return ((u * 2.0 - 1.0) * scale).astype(np.float32).reshape(shape)


def _dense(seed, idx, n_in, n_out):
    scale = float(np.sqrt(6.0 / (n_in + n_out)))
    return {"w": _fill(seed, idx, (n_in, n_out), scale),
            "b": np.zeros(n_out, np.float32)}


def init_params(seed: int) -> dict:
    """All model parameters as a {name: {w, b}} pytree of numpy
    arrays (JAX consumes them as-is)."""
    H = HIDDEN
    return {
        "link_embed": _dense(seed, 1, LINK_IN, H),
        "flow_embed": _dense(seed, 2, FLOW_IN, H),
        "flow_upd": _dense(seed, 3, 2 * H, H),
        "link_upd": _dense(seed, 4, 2 * H, H),
        "flow_out1": _dense(seed, 5, H, H),
        "flow_out2": _dense(seed, 6, H, 1),
        "link_out1": _dense(seed, 7, H, H),
        "link_out2": _dense(seed, 8, H, 1),
    }


def forward(params: dict, sample: dict):
    """(flow_pred (F,), link_pred (L,)) for one point sample.  Pure
    jnp; jit-compiled per sample shape by the caller."""
    import jax.numpy as jnp

    def dense(p, x):
        return x @ p["w"] + p["b"]

    lf = jnp.asarray(sample["link_feats"])
    ff = jnp.asarray(sample["flow_feats"])
    pairs = jnp.asarray(sample["pairs"])
    L = lf.shape[0]
    F = ff.shape[0]
    fi, li = pairs[:, 0], pairs[:, 1]
    link_h = jnp.tanh(dense(params["link_embed"], lf))
    flow_h = jnp.tanh(dense(params["flow_embed"], ff))
    for _ in range(T_STEPS):
        # flow reads its path's link states (sum-aggregated) …
        m_f = jnp.zeros((F, HIDDEN)).at[fi].add(link_h[li])
        flow_h = jnp.tanh(dense(params["flow_upd"],
                                jnp.concatenate([flow_h, m_f], 1)))
        # … then each link reads the flows crossing it.
        m_l = jnp.zeros((L, HIDDEN)).at[li].add(flow_h[fi])
        link_h = jnp.tanh(dense(params["link_upd"],
                                jnp.concatenate([link_h, m_l], 1)))
    flow_pred = dense(params["flow_out2"],
                      jnp.tanh(dense(params["flow_out1"],
                                     flow_h)))[:, 0]
    link_pred = dense(params["link_out2"],
                      jnp.tanh(dense(params["link_out1"],
                                     link_h)))[:, 0]
    return flow_pred, link_pred


def save(path: str, params: dict, meta: dict) -> None:
    """Flat .npz (numpy's container): parameters under
    '<layer>.<w|b>', the training metadata as a JSON sidecar
    string."""
    import json
    flat = {f"{k}.{kk}": v for k, v in params.items()
            for kk, v in v.items()}
    flat["__meta__"] = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8)
    np.savez(path, **flat)


def load(path: str):
    import json
    z = np.load(path)
    meta = json.loads(bytes(z["__meta__"]).decode())
    params: dict = {}
    for k in z.files:
        if k == "__meta__":
            continue
        layer, kk = k.rsplit(".", 1)
        params.setdefault(layer, {})[kk] = z[k]
    return params, meta
