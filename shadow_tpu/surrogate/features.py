"""Dataset -> per-point graph samples for the surrogate.

Entities follow RouteNet: LINKS are the directed edges of the point's
recorded topology (both directions of every undirected GML edge,
self-edges included — they are the intra-node host hop), FLOWS are
the dataset's receiver-vantage FCT rows, each carrying the sequence
of links its path crosses.  Paths come from a deterministic Dijkstra
(integer latency weights, lowest-index tie-break) over the SAME
topology the simulator routed on, so the surrogate sees the routing
the fabric actually used.

Per-link supervision: the peak sampled CoDel depth of the hosts at
the link's destination node (the inbound queue the link feeds); links
whose destination node was never sampled are masked out of the loss.

All features are plain float32 numpy — the model consumes them as-is.
"""

from __future__ import annotations

import heapq
import math

import numpy as np

from shadow_tpu.trace.events import iter_fb_records


def directed_links(topo: dict) -> list[tuple[int, int, int]]:
    """[(src node, dst node, latency_ns)] — both directions of every
    recorded edge, sorted; the link index space of one sample."""
    links = set()
    for u, v, lat in topo["edges"]:
        links.add((u, v, lat))
        links.add((v, u, lat))
    return sorted(links)


def shortest_path(links: list, n_nodes: int, src: int,
                  dst: int) -> list[int]:
    """Link-index sequence of the lowest-latency src->dst node path
    (Dijkstra, lowest-node-index tie-break — deterministic).  A
    same-node flow takes the node's self-edge."""
    if src == dst:
        for i, (u, v, _lat) in enumerate(links):
            if u == src and v == src:
                return [i]
        return []
    adj: dict = {}
    for i, (u, v, lat) in enumerate(links):
        if u != v:
            adj.setdefault(u, []).append((v, lat, i))
    dist = {src: 0}
    prev: dict = {}
    heap = [(0, src)]
    while heap:
        d, u = heapq.heappop(heap)
        if u == dst:
            break
        if d > dist.get(u, 1 << 62):
            continue
        for v, lat, i in sorted(adj.get(u, [])):
            nd = d + lat
            if nd < dist.get(v, 1 << 62):
                dist[v] = nd
                prev[v] = (u, i)
                heapq.heappush(heap, (nd, v))
    if dst not in prev and dst != src:
        return []
    path = []
    node = dst
    while node != src:
        u, i = prev[node]
        path.append(i)
        node = u
    return path[::-1]


def build_samples(ds) -> list[dict]:
    """One sample dict per dataset point:

    link_feats (L, 3)  log10 bw_down, log10 latency, is-self-edge
    flow_feats (F, 6)  log10 flow bytes, cc, dctcp_k/20, load,
                       log10 fan-in width, path length
    pairs      (P, 2)  (flow index, link index) path membership
    flow_t     (F,)    target: log10 FCT seconds... (log10 FCT ns)
    link_t     (L,)    target: log10(1 + peak CoDel depth at the
                       link's destination node)
    link_mask  (L,)    1 where the target is observed
    """
    samples = []
    for idx, pm in enumerate(ds.meta["points"]):
        topo = pm["topo"]
        feats = pm["features"]
        links = directed_links(topo)
        n_nodes = len(topo["nodes"])
        bw = {n["index"]: max(n["bw_down"], 1)
              for n in topo["nodes"]}
        link_feats = np.array(
            [[math.log10(bw[v]), math.log10(max(lat, 1)),
              1.0 if u == v else 0.0]
             for u, v, lat in links], dtype=np.float32)
        host_node = {int(h): n for h, n in topo["hosts"].items()}
        ip_host = {int(ip): h for ip, h in topo["host_ips"].items()}

        # Per-node peak sampled queue depth (FB records are per host).
        node_peak = {}
        for rec in iter_fb_records(ds.link_blobs[idx]):
            node = host_node.get(rec[1])
            if node is None:
                continue
            node_peak[node] = max(node_peak.get(node, 0), rec[3])
        link_t = np.array(
            [math.log10(1 + node_peak.get(v, 0)) for _u, v, _l
             in links], dtype=np.float32)
        link_mask = np.array(
            [1.0 if v in node_peak else 0.0 for _u, v, _l in links],
            dtype=np.float32)

        flow_feats, flow_t, pairs = [], [], []
        path_cache: dict = {}
        width = max(feats["fan_in"], feats["n_leaf"], 1)
        for row in ds.point_flows(idx):
            (t0, t1, host, _lp, _rp, rip, _flags, bin_, bout, _rtx,
             _marks) = row
            dst_node = host_node[host]
            peer = ip_host.get(rip)
            src_node = (host_node[peer] if peer is not None
                        else dst_node)
            key = (src_node, dst_node)
            if key not in path_cache:
                path_cache[key] = shortest_path(links, n_nodes,
                                                src_node, dst_node)
            path = path_cache[key]
            fi = len(flow_feats)
            flow_feats.append([
                math.log10(max(bin_, bout, 1)),
                1.0 if feats["cc"] == "dctcp" else 0.0,
                feats["dctcp_k"] / 20.0,
                feats["load"],
                math.log10(width + 1),
                float(len(path)),
            ])
            flow_t.append(math.log10(max(t1 - t0, 1)))
            pairs.extend((fi, li) for li in path)
        samples.append({
            "point_id": pm["point_id"],
            "features": feats,
            "link_feats": link_feats,
            "flow_feats": np.array(flow_feats, dtype=np.float32),
            "pairs": (np.array(pairs, dtype=np.int32)
                      if pairs else np.zeros((0, 2), np.int32)),
            "flow_t": np.array(flow_t, dtype=np.float32),
            "link_t": link_t,
            "link_mask": link_mask,
        })
    return samples
