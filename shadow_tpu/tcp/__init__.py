"""Sans-I/O TCP (ref: the reference's Rust tcp crate, src/lib/tcp/).

`TcpConnection` is a pure state machine: packets in, packets out, explicit
`now` on every call, timers surfaced as `next_timer_expiry()` — no
sockets, no host, no clock of its own. The same design goal as the
reference's `Dependencies` trait (src/lib/tcp/src/lib.rs:109-144): unit
tests drive it with a fake clock (tests/test_tcp_unit.py), and the socket
layer (host/socket_tcp.py) adapts it to the simulated kernel.

State that the congestion/retransmit logic reads every round (snd_una,
snd_nxt, cwnd, ssthresh, rto deadline, dupacks) is kept as plain integer
fields deliberately: the planned vectorized stepping lifts exactly those
fields into struct-of-arrays batches for the TPU path.
"""

from shadow_tpu.tcp.connection import (  # noqa: F401
    TcpConnection, CLOSED, LISTEN, SYN_SENT, SYN_RECEIVED, ESTABLISHED,
    FIN_WAIT_1, FIN_WAIT_2, CLOSING, TIME_WAIT, CLOSE_WAIT, LAST_ACK,
    STATE_NAMES)
