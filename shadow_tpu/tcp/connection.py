"""TCP connection state machine (sans-I/O).

Covers: three-way handshake (active + passive), bidirectional data
transfer with flow control (advertised windows), reno congestion control
(slow start, congestion avoidance, fast retransmit/recovery on 3 dupacks,
timeout backoff), Jacobson/Karn RTT estimation with integer-ns RTO,
out-of-order reassembly, graceful close through FIN states, TIME_WAIT,
and RST on unexpected segments.

Also modeled: window scaling (RFC 7323, ref window_scaling.rs)
negotiated via SYN options and applied to both advertised and received
windows; MSS clamping from the peer's SYN option; SACK (RFC 2018:
receiver reports reassembly runs in pure ACKs, sender marks covered
retransmit-queue entries and skips them on fast retransmit / partial
ack / RTO — ref the reference's C tcp.c SACK handling +
tcp_retransmit_tally.cc); delayed ACKs (ack every second in-order
segment or after a 40ms timer, immediate on out-of-order/FIN — Linux
quickack-style, off switch `delayed_ack=False`); Nagle (sub-MSS data
held while unacked data is in flight, off switch `nagle=False` or the
`nodelay` attribute, i.e. TCP_NODELAY); zero-window persist probes
(1-byte probe on exponential backoff while the peer advertises 0); and
a pluggable congestion-control seam with reno as the in-tree algorithm
(ref: tcp_cong.c/tcp_cong_reno.c — the reference likewise ships only
reno behind its ops table).

Timestamps (RFC 7323 TSopt, ref legacy tcp.c:141-142): every segment
carries its send time and echoes the last value received, so RTT
updates on every acked segment (suppressed during RTO backoff — Karn).
Simultaneous open is modeled (states below).  Deliberate
simplifications (documented for parity tracking in docs/PARITY.md):
no urgent data.

All arithmetic is integer (ns for time, mod-2^32 for sequence space) so
scalar and batched stepping agree bit-for-bit.
"""

from __future__ import annotations

from collections import deque

from shadow_tpu.net.packet import ECN_CE, TcpFlags, TcpHeader

# States (ref: src/lib/tcp/src/states.rs explicit state types).
CLOSED = 0
LISTEN = 1
SYN_SENT = 2
SYN_RECEIVED = 3
ESTABLISHED = 4
FIN_WAIT_1 = 5
FIN_WAIT_2 = 6
CLOSING = 7
TIME_WAIT = 8
CLOSE_WAIT = 9
LAST_ACK = 10

STATE_NAMES = {
    CLOSED: "closed", LISTEN: "listen", SYN_SENT: "syn-sent",
    SYN_RECEIVED: "syn-received", ESTABLISHED: "established",
    FIN_WAIT_1: "fin-wait-1", FIN_WAIT_2: "fin-wait-2", CLOSING: "closing",
    TIME_WAIT: "time-wait", CLOSE_WAIT: "close-wait", LAST_ACK: "last-ack",
}

MSS = 1460  # MTU 1500 - 40 header bytes
MAX_WINDOW = 65_535
# Linux-default sysctl maxima the buffer autotuner clamps against
# (ref definitions.h CONFIG_TCP_WMEM_MAX / CONFIG_TCP_RMEM_MAX), and
# the derived ceiling (10x) a dynamically-sized connection both grows
# toward and advertises window scale for — single source of truth so
# the scale can always represent the buffer.
WMEM_MAX = 4_194_304
RMEM_MAX = 6_291_456
RMEM_CEILING = 10 * RMEM_MAX


def choose_window_scale(window_ceiling: int) -> int:
    """RFC 7323 shift chosen at SYN time, Linux-style: the smallest
    scale that can advertise the LARGEST window this buffer could ever
    reach (the autotuner's ceiling when dynamic sizing is on) — the
    scale cannot change after the handshake.  Small fixed buffers get
    scale 0 and byte-granular windows."""
    scale = 0
    while window_ceiling > MAX_WINDOW and scale < 14:
        window_ceiling >>= 1
        scale += 1
    return scale
MAX_SACK_BLOCKS = 3             # with timestamps elided, 3 fit on wire

INIT_RTO_NS = 1_000_000_000     # RFC 6298 initial
MIN_RTO_NS = 200_000_000        # Linux-style floor
MAX_RTO_NS = 60_000_000_000
TIME_WAIT_NS = 60_000_000_000   # 2 * MSL with MSL=30s
DUPACK_THRESHOLD = 3
DELACK_NS = 40_000_000          # Linux TCP_DELACK_MIN

# DCTCP (RFC 8257, Linux tcp_dctcp.c shape; netplane.cpp twins).  All
# fixed-point so Python/C++/JAX compute the identical alpha: alpha is
# scaled by 2**DCTCP_SHIFT, the EWMA gain g is 1/2**DCTCP_G_SHIFT
# (Linux dctcp_shift_g default), and the per-window update is
#   alpha = min(MAX, alpha - (alpha >> G_SHIFT)
#               + (ce_bytes << (SHIFT - G_SHIFT)) // max(tot_bytes, 1))
# with the cwnd reduction on a congestion echo
#   cwnd = max(cwnd - ((cwnd * alpha) >> (SHIFT + 1)), 2 * mss).
DCTCP_SHIFT = 10
DCTCP_G_SHIFT = 4
DCTCP_MAX_ALPHA = 1024          # == 1 << DCTCP_SHIFT (alpha == 1.0)
# Congestion-controller ids (per-host `tcp: {cc: ...}` config; the SoA
# kernel's static c_cc column and the engine's TcpConn::cc use these).
CC_RENO = 0
CC_DCTCP = 1

_SEQ_MOD = 1 << 32


class RenoCongestion:
    """NewReno ops behind the pluggable seam (ref: tcp_cong.c ops table
    + tcp_cong_reno.c).  Owns cwnd/ssthresh; the connection reports ack
    and loss events."""

    name = "reno"

    def __init__(self, mss: int = MSS):
        self.mss = mss
        self.cwnd = 10 * mss  # RFC 6928 IW10
        # Infinite until the first loss event (ref tcp_cong_reno.c
        # ca_reno_init_: INT32_MAX; Linux TCP_INFINITE_SSTHRESH) —
        # slow start must not stop at an arbitrary ceiling.
        self.ssthresh = (1 << 31) - 1

    def on_new_ack(self, acked: int) -> None:
        if self.cwnd < self.ssthresh:
            # Slow start with ABC (RFC 3465, L=2*SMSS): delayed acks
            # covering two segments still double cwnd per RTT.
            self.cwnd += min(acked, 2 * self.mss)
        else:
            self.cwnd += max(1, self.mss * self.mss // self.cwnd)  # AIMD

    def on_fast_retransmit(self, flight: int) -> None:
        self.ssthresh = max(flight // 2, 2 * self.mss)
        self.cwnd = self.ssthresh + 3 * self.mss

    def on_recovery_dupack(self) -> None:
        self.cwnd += self.mss  # inflation

    def on_exit_recovery(self) -> None:
        self.cwnd = self.ssthresh

    def on_rto(self, flight: int) -> None:
        self.ssthresh = max(flight // 2, 2 * self.mss)
        self.cwnd = self.mss

    def on_ecn_reduce(self, flight: int) -> None:
        """RFC 3168 6.1.2 congestion response to ECE: same multiplica-
        tive decrease as a fast retransmit, but nothing retransmits."""
        self.ssthresh = max(flight // 2, 2 * self.mss)
        self.cwnd = self.ssthresh


class DctcpCongestion(RenoCongestion):
    """DCTCP (RFC 8257): reno growth, but the ECE response scales the
    cwnd cut by alpha — the EWMA fraction of acked bytes that carried a
    congestion echo — instead of halving.  All state is integer
    fixed-point (alpha scaled by 2**DCTCP_SHIFT) so the engine's
    TcpConn and the device kernel's conn columns compute bit-identical
    values.  The window accounting (win_end in sequence space) lives
    here too; the owning connection feeds it from _on_ack."""

    name = "dctcp"

    def __init__(self, mss: int = MSS):
        super().__init__(mss)
        self.alpha = DCTCP_MAX_ALPHA  # start fully conservative
        self.ce_acked = 0             # echo-marked bytes this window
        self.tot_acked = 0            # all acked bytes this window
        self.win_end = 0              # seq: conn sets to iss at birth

    def on_ecn_reduce(self, flight: int) -> None:
        cut = (self.cwnd * self.alpha) >> (DCTCP_SHIFT + 1)
        self.cwnd = max(self.cwnd - cut, 2 * self.mss)
        self.ssthresh = self.cwnd


CONGESTION_ALGOS = {"reno": RenoCongestion, "dctcp": DctcpCongestion}


def seq_add(a: int, b: int) -> int:
    return (a + b) % _SEQ_MOD


def seq_sub(a: int, b: int) -> int:
    """Signed distance a-b in sequence space."""
    d = (a - b) % _SEQ_MOD
    return d - _SEQ_MOD if d >= _SEQ_MOD // 2 else d


def seq_lt(a: int, b: int) -> bool:
    return seq_sub(a, b) < 0


def seq_leq(a: int, b: int) -> bool:
    return seq_sub(a, b) <= 0


class TcpConnection:
    """One direction-pair of TCP state. Emitted segments accumulate in
    `outbox` as (TcpHeader, payload_bytes); the owner drains it."""

    def __init__(self, iss: int, recv_buf_max: int = 174_760,
                 send_buf_max: int = 131_072, congestion: str = "reno",
                 delayed_ack: bool = True, nagle: bool = True,
                 window_ceiling: int | None = None, ecn: bool = False):
        self.state = CLOSED
        self.iss = iss % _SEQ_MOD
        # SYN-time scale choice covers the largest window the receive
        # buffer can ever grow to (autotuning ceiling when enabled).
        self._wscale_offer = choose_window_scale(
            window_ceiling if window_ceiling is not None else recv_buf_max)

        # Send side.
        self.snd_una = self.iss
        self.snd_nxt = self.iss
        self.snd_wnd = MSS  # until the peer advertises
        self.send_buf: deque = deque()   # byte chunks awaiting segmentation
        self.send_buf_len = 0
        self.send_buf_max = send_buf_max
        self.snd_fin_pending = False     # app closed; FIN after data drains
        self.fin_seq: int | None = None  # seq consumed by our FIN
        # Retransmission queue: list of [seq, payload, is_fin, sent_at,
        # retransmitted, sacked] — ordered by seq.
        self.rtx: list = []

        # Receive side.
        self.irs = 0
        self.rcv_nxt = 0
        self.recv_buf: deque = deque()
        self.recv_buf_len = 0
        self.recv_buf_max = recv_buf_max
        self.reassembly: dict[int, bytes] = {}  # seq -> payload (future)
        self.peer_fin_seq: int | None = None   # set once the FIN is
        self.pending_fin_seq: int | None = None  # ...processed in order

        # Window scaling (RFC 7323; ref window_scaling.rs): we always
        # offer our chosen scale; active only if the peer offers too.
        self.our_wscale = 0    # shift applied to windows we advertise
        self.peer_wscale = 0   # shift applied to windows we receive
        self.eff_mss = MSS     # clamped by the peer's MSS option

        # Delayed ACK (RFC 1122 4.2.3.2) + Nagle (RFC 896).
        self.delayed_ack = delayed_ack
        self.nagle = nagle
        self.nodelay = False           # TCP_NODELAY
        self._delack_deadline: int | None = None
        self._segs_since_ack = 0

        # Zero-window persist probing.
        self._persist_deadline: int | None = None
        self._persist_interval = 0

        # Congestion control behind the pluggable seam (tcp_cong.c).
        self.cong = CONGESTION_ALGOS[congestion]()
        self.dupacks = 0
        self.in_fast_recovery = False
        self.recover = self.iss

        # ECN (RFC 3168; netplane.cpp TcpConn twins).  `ecn_on` is the
        # per-host config wish; `ecn_active` is negotiated at the
        # handshake (ECN-setup SYN carries ECE|CWR, the SYN-ACK
        # answers with ECE).  The receiver latches `ece_latch` on a
        # CE-marked arrival and echoes ECE on every ACK until a CWR
        # arrives; the sender reacts to ECE at most once per window
        # (`ecn_cwr_end`) and announces the cut with CWR on its next
        # fresh data segment (`cwr_pending`).
        self.ecn_on = bool(ecn)
        self.ecn_active = False
        self.ece_latch = False
        self.cwr_pending = False
        self.ecn_cwr_end = self.iss
        if isinstance(self.cong, DctcpCongestion):
            self.cong.win_end = self.iss

        # RTT/RTO (integer ns, RFC 6298 + RFC 7323 timestamps).  Every
        # segment carries its send time; the receiver echoes the last
        # value it saw, so ANY acked segment yields an RTT sample —
        # the reference's legacy-stack behavior (tcp.c:141-142,
        # 2356-2358: per-segment timestampValue/timestampEcho, sampling
        # suppressed while in RTO backoff, Karn via the echo discipline).
        self.srtt = 0
        self.rttvar = 0
        self.rto = INIT_RTO_NS
        self.rto_deadline: int | None = None
        self.time_wait_deadline: int | None = None
        self._ts_recent = 0      # last timestamp value received
        self._rto_backoff = 0    # RTO doublings since last fwd progress

        self.outbox: deque = deque()  # (TcpHeader, payload)
        self.error: str | None = None  # set on RST / fatal
        self.syn_retries = 0

        # Counters for stats/debug.
        self.retransmit_count = 0
        self.segments_sent = 0
        self.segments_received = 0
        self.sacked_skip_count = 0  # retransmissions avoided via SACK
        # Receiver discards (sim-netstat TEL_REASM_FULL /
        # TEL_RECVWIN_TRUNC; netplane.cpp twins): payload the receiver
        # refused — a segment beyond the reassembly window, or in-order
        # bytes past the receive buffer.  The socket layer folds the
        # per-packet delta into the host's drop-cause counters.
        self.reasm_discards = 0
        self.rcvwin_trunc = 0
        # Fabric-observatory flow lifecycle (netplane.cpp TcpConn
        # twins; trace/fabricstat.py packs them into FCT_REC records):
        # first/last simulated ns any payload byte was FIRST-sent or
        # delivered in order on this endpoint, plus the byte counts.
        # Retransmissions touch neither — bytes_out is the flow size.
        self.fct_first = -1
        self.fct_last = -1
        self.fct_bytes_in = 0
        self.fct_bytes_out = 0
        # Per-flow ECN mark-rate telemetry (netplane.cpp TcpConn twin;
        # the `marks` column of both TEL_REC and FCT_REC): cumulative
        # CE-marked arrivals this endpoint OBSERVED — counted exactly
        # where the RFC 3168 receiver latches ECE, so all three
        # execution paths agree byte-for-byte.
        self.ce_seen = 0

    def _fct_touch(self, nbytes: int, now: int, inbound: bool) -> None:
        if self.fct_first < 0:
            self.fct_first = now
        self.fct_last = now
        if inbound:
            self.fct_bytes_in += nbytes
        else:
            self.fct_bytes_out += nbytes

    # Congestion variables live on the algorithm object; these views
    # keep call sites and tests readable.
    @property
    def cwnd(self) -> int:
        return self.cong.cwnd

    @property
    def ssthresh(self) -> int:
        return self.cong.ssthresh

    # ------------------------------------------------------------------
    # App-side API
    # ------------------------------------------------------------------

    def open_active(self, now: int) -> None:
        """connect(): emit SYN (states.rs Init->SynSent). The SYN offers
        our MSS and window-scale options (RFC 7323: the scale only
        activates if the peer's SYN offers one too), and — with ecn_on
        — the RFC 3168 ECN-setup flags ECE|CWR."""
        assert self.state == CLOSED
        self.state = SYN_SENT
        flags = TcpFlags.SYN
        if self.ecn_on:
            flags |= TcpFlags.ECE | TcpFlags.CWR
        self._emit(flags, seq=self.iss, payload=b"", now=now,
                   track=True, mss=MSS, window_scale=self._wscale_offer)
        self.snd_nxt = seq_add(self.iss, 1)

    def open_passive(self) -> None:
        assert self.state == CLOSED
        self.state = LISTEN

    def send_space(self) -> int:
        return self.send_buf_max - self.send_buf_len

    def write(self, data: bytes, now: int) -> int:
        """Append app data; returns bytes accepted (0 = would block)."""
        if self.state not in (ESTABLISHED, CLOSE_WAIT):
            raise ConnectionError(f"write in {STATE_NAMES[self.state]}")
        if self.snd_fin_pending:
            raise ConnectionError("write after close")
        n = min(len(data), self.send_space())
        if n > 0:
            self.send_buf.append(bytes(data[:n]))
            self.send_buf_len += n
            self._push_data(now)
        return n

    def readable_bytes(self) -> int:
        return self.recv_buf_len

    def at_eof(self) -> bool:
        return (self.peer_fin_seq is not None and self.recv_buf_len == 0
                and not self.reassembly)

    def peek(self, n: int) -> bytes:
        """MSG_PEEK: copy up to n readable bytes without consuming
        (header sniffing — wget peeks the HTTP response)."""
        out = bytearray()
        for chunk in self.recv_buf:
            if n <= 0:
                break
            take = chunk[:n]
            out += take
            n -= len(take)
        return bytes(out)

    def read(self, n: int, now: int) -> bytes:
        window_before = self._recv_window()
        out = bytearray()
        while n > 0 and self.recv_buf:
            chunk = self.recv_buf[0]
            if len(chunk) <= n:
                out += chunk
                n -= len(chunk)
                self.recv_buf.popleft()
            else:
                out += chunk[:n]
                self.recv_buf[0] = chunk[n:]
                n = 0
        if out:
            self.recv_buf_len -= len(out)
            # Window-update ACK only when the window was pinched shut —
            # an ACK per read() would flood the wire with pure acks.
            if window_before < MSS and self._recv_window() >= MSS and \
                    self.state in (ESTABLISHED, FIN_WAIT_1, FIN_WAIT_2):
                self._emit_ack(now)
        return bytes(out)

    def close(self, now: int) -> None:
        """App close: FIN once the send buffer drains
        (states.rs Established->FinWait1 / CloseWait->LastAck)."""
        if self.state in (CLOSED, LISTEN):
            self.state = CLOSED
            return
        if self.state == SYN_SENT:
            self.state = CLOSED
            self.rto_deadline = None
            self.rtx.clear()
            return
        if self.snd_fin_pending or self.fin_seq is not None:
            return
        self.snd_fin_pending = True
        if self.state == ESTABLISHED:
            self.state = FIN_WAIT_1
        elif self.state == CLOSE_WAIT:
            self.state = LAST_ACK
        self._push_data(now)

    def abort(self, now: int) -> None:
        """RST out, state torn down."""
        if self.state not in (CLOSED, LISTEN, TIME_WAIT):
            self._emit(TcpFlags.RST | TcpFlags.ACK, seq=self.snd_nxt,
                       payload=b"", now=now)
        self.state = CLOSED
        self.error = self.error or "aborted"
        self.rto_deadline = None
        self._delack_deadline = None
        self._persist_deadline = None

    # ------------------------------------------------------------------
    # Timers
    # ------------------------------------------------------------------

    def next_timer_expiry(self) -> int | None:
        candidates = [t for t in (self.rto_deadline,
                                  self.time_wait_deadline,
                                  self._delack_deadline,
                                  self._persist_deadline) if t is not None]
        return min(candidates) if candidates else None

    def on_timer(self, now: int) -> None:
        if self.time_wait_deadline is not None \
                and now >= self.time_wait_deadline:
            self.time_wait_deadline = None
            if self.state == TIME_WAIT:
                self.state = CLOSED
        if self._delack_deadline is not None \
                and now >= self._delack_deadline:
            if self.state in (CLOSED, LISTEN):
                self._delack_deadline = None
            else:
                self._emit_ack(now)  # clears the deadline
        if self._persist_deadline is not None \
                and now >= self._persist_deadline:
            self._on_persist(now)
        if self.rto_deadline is not None and now >= self.rto_deadline:
            self._on_rto(now)

    def _on_persist(self, now: int) -> None:
        """Zero-window probe: 1 byte of new data past the window edge.
        Linux-style exponential backoff; the probe is tracked in the rtx
        queue so an opening window acks it normally."""
        self._persist_deadline = None
        if self.snd_wnd > 0 or not self.send_buf or self.rtx:
            return
        chunk = self._take_from_send_buf(1)
        self._emit(self._data_flags(), seq=self.snd_nxt,
                   payload=chunk, now=now, track=True)
        self.snd_nxt = seq_add(self.snd_nxt, 1)
        self._fct_touch(1, now, inbound=False)
        self._persist_interval = min(self._persist_interval * 2
                                     or self.rto, MAX_RTO_NS)
        self._persist_deadline = now + self._persist_interval

    def _on_rto(self, now: int) -> None:
        """Retransmission timeout (RFC 6298 5.4-5.7 + reno reset)."""
        if not self.rtx:
            self.rto_deadline = None
            return
        # Handshake gives up after 6 backoffs (Linux tcp_syn_retries):
        # connecting to a dead/closed port must fail, not hang forever.
        if self.state in (SYN_SENT, SYN_RECEIVED):
            self.syn_retries += 1
            if self.syn_retries > 6:
                self.error = "connection timed out"
                self.state = CLOSED
                self.rto_deadline = None
                self.rtx.clear()
                return
        flight = seq_sub(self.snd_nxt, self.snd_una)
        self.cong.on_rto(flight)
        self.dupacks = 0
        self.in_fast_recovery = False
        # SACK reneging (RFC 2018 8; ref tcp.c clears its scoreboard):
        # after an RTO the receiver may have discarded data it SACKed,
        # so forget every mark and retransmit from the head — a mark
        # kept here could skip a hole the receiver no longer holds,
        # stalling the transfer forever.
        for seg in self.rtx:
            seg[5] = False
        self.rto = min(self.rto * 2, MAX_RTO_NS)
        self._rto_backoff += 1  # suppress RTT sampling until fwd progress
        self._retransmit_one(now)
        self.rto_deadline = now + self.rto

    # ------------------------------------------------------------------
    # Packet ingress
    # ------------------------------------------------------------------

    def on_packet(self, hdr: TcpHeader, payload: bytes, now: int,
                  ecn: int = 0) -> None:
        self.segments_received += 1
        if self.state == CLOSED:
            return
        if hdr.flags & TcpFlags.RST:
            self._on_rst(hdr)
            return
        # RFC 3168 receiver: a CWR ends the echo episode, a CE-marked
        # arrival (re)starts it — in that order, so a segment carrying
        # both leaves the latch set.  `ecn` is the packet's IP-header
        # codepoint (the socket layer threads it through; the queues
        # rewrote ECT(0) to CE when the marking law fired).
        if self.ecn_active:
            if hdr.flags & TcpFlags.CWR:
                self.ece_latch = False
            if ecn == ECN_CE:
                self.ece_latch = True
                self.ce_seen += 1
        # RFC 7323 timestamp processing on EVERY segment (ref
        # tcp.c:2356-2358, plus the TS.Recent update rule the RFC adds:
        # only a segment covering the last ack point may update the
        # value to echo — a late-arriving old duplicate must not wind
        # ts_recent back, or its dup-ack's echo would feed an
        # RTO-stall-sized sample into srtt).  Values are stamped as
        # now+1 so a segment sent at sim time 0 still carries the
        # option (0 = absent).
        if hdr.timestamp and self.state != SYN_SENT:
            # (SYN_SENT records in its handler, after rcv_nxt exists.)
            seg_span = len(payload) \
                + (1 if hdr.flags & TcpFlags.FIN else 0)
            if seg_span == 0:
                seg_span = 1  # pure ACK sits at the ack point
            if seq_leq(hdr.seq, self.rcv_nxt) and \
                    seq_lt(self.rcv_nxt, seq_add(hdr.seq, seg_span)):
                self._ts_recent = hdr.timestamp
        # RTTM rule: sample only from a segment that ACKNOWLEDGES NEW
        # DATA — an echo held across an application-idle gap must not
        # feed an idle-sized sample into srtt.
        if hdr.timestamp_echo and self._rto_backoff == 0 \
                and (hdr.flags & TcpFlags.ACK) \
                and seq_lt(self.snd_una, hdr.ack) \
                and seq_leq(hdr.ack, self.snd_nxt):
            self._update_rtt(now - (hdr.timestamp_echo - 1))
        if self.state == LISTEN:
            # Owner (listener socket) is responsible for spawning child
            # connections; a LISTEN connection itself ignores non-SYN.
            return
        if self.state == SYN_SENT:
            self._on_packet_syn_sent(hdr, now)
            return
        # --- synchronized states ---
        if hdr.flags & TcpFlags.SYN:
            if self.state == SYN_RECEIVED and \
                    (hdr.flags & TcpFlags.ACK) and \
                    hdr.ack == self.snd_nxt:
                # Simultaneous open completing: the peer's SYN-ACK acks
                # our SYN.  Handle inline — _on_ack would scale the
                # window, but SYN segments carry UNSCALED windows
                # (RFC 7323 2.2), same as _on_packet_syn_sent.
                self.snd_una = hdr.ack
                self.snd_wnd = hdr.window
                self._clear_acked()
                self.state = ESTABLISHED
                self._emit_ack(now)
                self._push_data(now)
                return
            if self.state == SYN_RECEIVED and hdr.seq == seq_sub(
                    self.rcv_nxt, 1) % _SEQ_MOD:
                # Re-sent SYN (our SYN-ACK was lost): re-answer it.
                self._emit_synack(now)
                return
            self._emit_ack(now)
            return
        if not (hdr.flags & TcpFlags.ACK):
            return
        self._on_ack(hdr, now, is_pure_ack=not payload
                     and not (hdr.flags & TcpFlags.FIN))
        if payload:
            self._on_data(hdr.seq, payload, now)
        if hdr.flags & TcpFlags.FIN:
            self._on_fin(hdr, payload, now)

    def accept_syn(self, hdr: TcpHeader, now: int) -> None:
        """Passive open: called on a child connection created by a
        listener for an incoming SYN. Negotiates MSS and window scaling
        from the SYN's options (windows in SYN segments are unscaled,
        RFC 7323 2.2)."""
        assert self.state in (CLOSED, LISTEN)
        self.irs = hdr.seq
        self.rcv_nxt = seq_add(hdr.seq, 1)
        if hdr.timestamp:
            self._ts_recent = hdr.timestamp  # SYN's value: echo in SYN-ACK
        self.snd_wnd = hdr.window
        # ECN-setup SYN (RFC 3168 6.1.1): accept iff we want ECN too.
        self.ecn_active = self.ecn_on and (
            hdr.flags & (TcpFlags.ECE | TcpFlags.CWR)
        ) == (TcpFlags.ECE | TcpFlags.CWR)
        self._negotiate_options(hdr)
        self.state = SYN_RECEIVED
        self._emit_synack(now)
        self.snd_nxt = seq_add(self.iss, 1)

    def _negotiate_options(self, hdr: TcpHeader) -> None:
        if hdr.mss is not None:
            self.eff_mss = min(MSS, hdr.mss)
            # Negotiation happens before any data flows: rebuild the
            # congestion state so IW10/ssthresh are sized for the real
            # MSS rather than the 1460-byte default.
            self.cong = type(self.cong)(mss=self.eff_mss)
            if isinstance(self.cong, DctcpCongestion):
                self.cong.win_end = self.iss  # nothing acked yet
        if hdr.window_scale is not None:
            self.our_wscale = self._wscale_offer
            self.peer_wscale = min(hdr.window_scale, 14)

    def _emit_synack(self, now: int) -> None:
        flags = TcpFlags.SYN | TcpFlags.ACK
        if self.ecn_active:
            flags |= TcpFlags.ECE  # ECN-setup SYN-ACK (RFC 3168 6.1.1)
        self._emit(flags, seq=self.iss, payload=b"",
                   now=now, track=(self.snd_nxt == self.iss), mss=MSS,
                   window_scale=(self._wscale_offer if self.our_wscale
                                 else None))

    def _on_packet_syn_sent(self, hdr: TcpHeader, now: int) -> None:
        if (hdr.flags & TcpFlags.ACK) and hdr.ack != self.snd_nxt:
            # RFC 793 SYN-SENT first check: an unacceptable ACK —
            # with OR without SYN (a delayed SYN-ACK from a previous
            # incarnation of a reused 4-tuple) — answers
            # <SEQ=SEG.ACK><CTL=RST>; our state is unchanged so the
            # handshake can still complete on retry.
            self._emit(TcpFlags.RST, seq=hdr.ack, payload=b"", now=now)
            return
        if (hdr.flags & (TcpFlags.SYN | TcpFlags.ACK)) == \
                (TcpFlags.SYN | TcpFlags.ACK):
            self.irs = hdr.seq
            self.rcv_nxt = seq_add(hdr.seq, 1)
            if hdr.timestamp:
                self._ts_recent = hdr.timestamp
            self.snd_una = hdr.ack
            self.snd_wnd = hdr.window
            # ECN-setup SYN-ACK carries ECE without CWR (RFC 3168
            # 6.1.1); anything else leaves the connection not-ECT.
            self.ecn_active = self.ecn_on \
                and bool(hdr.flags & TcpFlags.ECE) \
                and not (hdr.flags & TcpFlags.CWR)
            self._negotiate_options(hdr)
            self._clear_acked()
            self.state = ESTABLISHED
            self._emit_ack(now)
        elif hdr.flags & TcpFlags.SYN:
            # Simultaneous open (RFC 793 fig. 8; ref states.rs models
            # SynSent -> SynReceived): both ends sent SYNs that crossed.
            # Adopt the peer's ISN, answer SYN-ACK, and wait in
            # SYN_RECEIVED for the ack of our own SYN.  Our original
            # SYN stays on the rtx queue: if this SYN-ACK is lost, the
            # bare-SYN retransmit re-triggers the peer's own re-ack.
            self.irs = hdr.seq
            self.rcv_nxt = seq_add(hdr.seq, 1)
            if hdr.timestamp:
                self._ts_recent = hdr.timestamp
            self.snd_wnd = hdr.window
            self._negotiate_options(hdr)
            self.state = SYN_RECEIVED
            self._emit_synack(now)

    def _on_rst(self, hdr: TcpHeader) -> None:
        self.error = "connection reset"
        self.state = CLOSED
        self.rto_deadline = None
        self.time_wait_deadline = None
        self._delack_deadline = None
        self._persist_deadline = None

    def _on_ack(self, hdr: TcpHeader, now: int,
                is_pure_ack: bool = True) -> None:
        ack = hdr.ack
        if seq_lt(self.snd_nxt, ack):
            # Acks something we never sent.
            self._emit_ack(now)
            return
        # Post-handshake windows arrive scaled (RFC 7323 2.2: every
        # segment except the SYN itself).
        wnd = hdr.window << self.peer_wscale
        window_changed = wnd != self.snd_wnd
        self.snd_wnd = wnd
        if wnd > 0 and self._persist_deadline is not None:
            self._persist_deadline = None
            self._persist_interval = 0
        if hdr.sack_blocks:
            self._mark_sacked(hdr.sack_blocks)
        # ECN sender side (RFC 3168 6.1.2 + RFC 8257 3.3), BEFORE the
        # new-ack/dupack dispatch so snd_una still holds the pre-ack
        # value — the C++ TcpConn and the SoA kernel mirror this exact
        # sequence so the arithmetic is bit-identical on every path.
        ecn_reduced = False
        if self.ecn_active:
            ece = bool(hdr.flags & TcpFlags.ECE)
            if isinstance(self.cong, DctcpCongestion) \
                    and seq_lt(self.snd_una, ack):
                c = self.cong
                acked = seq_sub(ack, self.snd_una)
                c.tot_acked += acked
                if ece:
                    c.ce_acked += acked
                if seq_lt(c.win_end, ack):
                    # Window boundary: fold this window's echo fraction
                    # into alpha (fixed-point EWMA, gain 1/2**G_SHIFT).
                    c.alpha = min(
                        DCTCP_MAX_ALPHA,
                        c.alpha - (c.alpha >> DCTCP_G_SHIFT)
                        + (c.ce_acked << (DCTCP_SHIFT - DCTCP_G_SHIFT))
                        // max(c.tot_acked, 1))
                    c.ce_acked = 0
                    c.tot_acked = 0
                    c.win_end = self.snd_nxt
            if ece and not self.in_fast_recovery \
                    and seq_lt(self.ecn_cwr_end, ack):
                # At most one cut per window; announce it with CWR on
                # the next fresh data segment.
                self.cong.on_ecn_reduce(self._flight())
                self.ecn_cwr_end = self.snd_nxt
                self.cwr_pending = True
                ecn_reduced = True
        if seq_lt(self.snd_una, ack):
            self._handle_new_ack(ack, now, ecn_reduced=ecn_reduced)
        elif ack == self.snd_una and self.rtx and is_pure_ack \
                and not window_changed:
            # RFC 5681: only payload-free, window-unchanged acks count as
            # duplicates — a peer streaming its own data repeats our ack
            # number without implying loss.
            self._handle_dupack(now)
        # Handshake completion for passive side.
        if self.state == SYN_RECEIVED and seq_lt(self.iss, ack):
            self.state = ESTABLISHED
        self._advance_close_states(now)
        self._push_data(now)

    def _handle_new_ack(self, ack: int, now: int,
                        ecn_reduced: bool = False) -> None:
        acked = seq_sub(ack, self.snd_una)
        self.snd_una = ack
        self.dupacks = 0
        self._clear_acked()
        self._rto_backoff = 0  # forward progress re-enables sampling
        if self.srtt > 0:
            # Forward progress undoes exponential RTO backoff.  Without
            # this, sustained loss walks rto to the 60s cap and every
            # remaining hole costs a full max-RTO — transfers that
            # should take seconds take hours.
            self.rto = min(max(self.srtt + max(4 * self.rttvar, 1_000_000),
                               MIN_RTO_NS), MAX_RTO_NS)
        if self.in_fast_recovery:
            if seq_lt(self.recover, ack) or ack == self.recover:
                self.in_fast_recovery = False
                self.cong.on_exit_recovery()
            else:
                # Partial ack: retransmit next hole immediately.
                self._retransmit_one(now)
        elif not ecn_reduced:
            # An ack that just triggered the ECN cut must not also
            # grow the window it shrank.
            self.cong.on_new_ack(acked)
        # RTO restart (RFC 6298 5.3).
        self.rto_deadline = (now + self.rto) if self.rtx else None

    def _handle_dupack(self, now: int) -> None:
        self.dupacks += 1
        if self.in_fast_recovery:
            self.cong.on_recovery_dupack()
            self._push_data(now)
        elif self.dupacks == DUPACK_THRESHOLD:
            flight = seq_sub(self.snd_nxt, self.snd_una)
            self.cong.on_fast_retransmit(flight)
            self.in_fast_recovery = True
            self.recover = self.snd_nxt
            self._retransmit_one(now)

    # --- SACK scoreboard (RFC 2018; ref tcp_retransmit_tally.cc) ---

    def _mark_sacked(self, blocks) -> None:
        """Mark rtx entries wholly covered by a reported block. Blocks
        are (start, end) in the peer's receive-sequence space."""
        for seg in self.rtx:
            if seg[5]:
                continue
            seq = seg[0]
            end = seq_add(seq, len(seg[1]) + (1 if seg[2] else 0)
                          + (1 if seg[1] == b"" and not seg[2] else 0))
            for start, stop in blocks:
                if seq_leq(start, seq) and seq_leq(end, stop):
                    seg[5] = True
                    self.sacked_skip_count += 1
                    break

    def _retransmit_one(self, now: int) -> None:
        """Retransmit the first hole: the earliest rtx entry the peer has
        not SACKed (falling back to the head if everything is marked —
        the peer may have renegged)."""
        if not self.rtx:
            return
        seg = next((s for s in self.rtx if not s[5]), self.rtx[0])
        seg[3] = now
        seg[4] = True
        self.retransmit_count += 1
        self._transmit_segment(seg[0], seg[1], seg[2], now)

    def _clear_acked(self) -> None:
        """Drop fully-acked segments from the rtx queue.  (RTT comes
        from timestamp echoes, not from rtx entries.)"""
        while self.rtx:
            seq, payload, is_fin, sent_at, retransmitted, sacked = self.rtx[0]
            # Sequence space consumed: data bytes, or 1 for SYN/FIN.
            end = seq_add(seq, len(payload) + (1 if is_fin else 0)
                          + (1 if payload == b"" and not is_fin else 0))
            if seq_leq(end, self.snd_una):
                self.rtx.pop(0)
            else:
                break

    def _update_rtt(self, sample: int) -> None:
        if sample <= 0:
            sample = 1
        if self.srtt == 0:
            self.srtt = sample
            self.rttvar = sample // 2
        else:
            err = abs(self.srtt - sample)
            self.rttvar = (3 * self.rttvar + err) // 4
            self.srtt = (7 * self.srtt + sample) // 8
        self.rto = self.srtt + max(4 * self.rttvar, 1_000_000)
        self.rto = min(max(self.rto, MIN_RTO_NS), MAX_RTO_NS)

    # ------------------------------------------------------------------
    # Data ingress / reassembly
    # ------------------------------------------------------------------

    def _recv_window(self) -> int:
        """True receive window in bytes, bounded by what the negotiated
        scale can represent on the wire."""
        cap = MAX_WINDOW << self.our_wscale
        return min(cap, max(0, self.recv_buf_max - self.recv_buf_len))

    def _wire_window(self, flags: int) -> int:
        """The 16-bit window field: scaled except in SYN segments."""
        win = self._recv_window()
        if flags & TcpFlags.SYN:
            return min(win, MAX_WINDOW)
        return min(win >> self.our_wscale, MAX_WINDOW)

    def _sack_blocks(self) -> tuple:
        """Contiguous runs held in reassembly, as (start, end) pairs in
        ascending sequence order, capped at MAX_SACK_BLOCKS (RFC 2018).
        Deterministic: derived purely from the reassembly map."""
        if not self.reassembly:
            return ()
        seqs = sorted(self.reassembly, key=lambda s: seq_sub(s, self.rcv_nxt))
        blocks = []
        start = end = None
        for s in seqs:
            e = seq_add(s, len(self.reassembly[s]))
            if start is None:
                start, end = s, e
            elif seq_leq(s, end):
                if seq_lt(end, e):
                    end = e
            else:
                blocks.append((start, end))
                start, end = s, e
        blocks.append((start, end))
        return tuple(blocks[:MAX_SACK_BLOCKS])

    def _ack_data(self, now: int, force: bool = False) -> None:
        """Ack in-order data: immediately every second segment (or when
        anything unusual is pending — holes, a gap just filled, FIN, a
        pinched window), else arm the 40ms delack timer (RFC 1122
        4.2.3.2; off switch delayed_ack=False)."""
        self._segs_since_ack += 1
        if (force or not self.delayed_ack or self._segs_since_ack >= 2
                or self.reassembly or self.peer_fin_seq is not None
                or self._recv_window() < self.eff_mss):
            self._emit_ack(now)
        elif self._delack_deadline is None:
            self._delack_deadline = now + DELACK_NS

    def _on_data(self, seq: int, payload: bytes, now: int) -> None:
        if self.state not in (ESTABLISHED, FIN_WAIT_1, FIN_WAIT_2):
            return
        # Trim anything already received.
        offset = seq_sub(self.rcv_nxt, seq)
        if offset >= len(payload):
            self._emit_ack(now)  # pure duplicate
            return
        if offset > 0:
            payload = payload[offset:]
            seq = self.rcv_nxt
        if seq != self.rcv_nxt:
            # Future segment: stash (bounded by the advertised window).
            if seq_sub(seq, self.rcv_nxt) < self.recv_buf_max:
                self.reassembly.setdefault(seq, payload)
            else:
                self.reasm_discards += 1  # beyond the window: discard
            self._emit_ack(now)  # dupack → sender fast-retransmits
            return
        # In-order: deliver, then drain any contiguous stashed segments.
        had_holes = bool(self.reassembly)
        rcv0 = self.rcv_nxt
        self._deliver(payload)
        while self.rcv_nxt in self.reassembly:
            self._deliver(self.reassembly.pop(self.rcv_nxt))
        # Fabric-observatory flow lifecycle: the rcv_nxt advance IS the
        # in-order delivered byte count (computed before any FIN
        # consumes its sequence slot below).
        delivered = seq_sub(self.rcv_nxt, rcv0)
        if delivered > 0:
            self._fct_touch(delivered, now, inbound=True)
        # An out-of-order FIN becomes processable once the gap fills.
        if self.pending_fin_seq == self.rcv_nxt:
            self._process_fin(now)
        self._ack_data(now, force=had_holes)

    def _deliver(self, payload: bytes) -> None:
        space = self.recv_buf_max - self.recv_buf_len
        take = payload[:space]
        if take:
            self.recv_buf.append(take)
            self.recv_buf_len += len(take)
            self.rcv_nxt = seq_add(self.rcv_nxt, len(take))
        if len(payload) > len(take):
            self.rcvwin_trunc += 1
        # Bytes beyond buffer space are NOT acked; the shrunken advertised
        # window tells the sender to back off and retransmit later.

    def _on_fin(self, hdr: TcpHeader, payload: bytes, now: int) -> None:
        if self.peer_fin_seq is not None:
            # Retransmitted FIN (our ACK was lost, e.g. in TIME_WAIT):
            # just re-ACK.
            self._emit_ack(now)
            return
        fin_seq = seq_add(hdr.seq, len(payload))
        if fin_seq != self.rcv_nxt:
            # FIN beyond data we haven't received: wait for reassembly.
            self.pending_fin_seq = fin_seq
            self._emit_ack(now)
            return
        self._process_fin(now)
        self._emit_ack(now)

    def _process_fin(self, now: int) -> None:
        self.peer_fin_seq = self.rcv_nxt
        self.pending_fin_seq = None
        self.rcv_nxt = seq_add(self.rcv_nxt, 1)
        if self.state == ESTABLISHED:
            self.state = CLOSE_WAIT
        elif self.state == FIN_WAIT_1:
            self.state = CLOSING
        elif self.state == FIN_WAIT_2:
            self._enter_time_wait(now)
        self._advance_close_states(now)

    def _advance_close_states(self, now: int) -> None:
        fin_acked = (self.fin_seq is not None
                     and seq_lt(self.fin_seq, self.snd_una))
        if self.state == FIN_WAIT_1 and fin_acked:
            self.state = FIN_WAIT_2
        elif self.state == CLOSING and fin_acked:
            self._enter_time_wait(now)
        elif self.state == LAST_ACK and fin_acked:
            self.state = CLOSED
            self.rto_deadline = None

    def _enter_time_wait(self, now: int) -> None:
        self.state = TIME_WAIT
        self.rto_deadline = None
        self.time_wait_deadline = now + TIME_WAIT_NS

    # ------------------------------------------------------------------
    # Segment egress
    # ------------------------------------------------------------------

    def _flight(self) -> int:
        return seq_sub(self.snd_nxt, self.snd_una)

    def _push_data(self, now: int) -> None:
        """Segmentize send_buf within min(cwnd, peer window), in
        eff_mss-sized segments. Nagle (RFC 896): hold sub-MSS data while
        anything is unacked, unless nodelay or a FIN is pending."""
        if self.state not in (ESTABLISHED, CLOSE_WAIT, FIN_WAIT_1,
                              CLOSING, LAST_ACK):
            return
        window = min(self.cwnd, self.snd_wnd)
        while self.send_buf and self._flight() < window:
            budget = min(window - self._flight(), self.eff_mss)
            if (self.nagle and not self.nodelay and not self.snd_fin_pending
                    and self.send_buf_len < min(budget, self.eff_mss)
                    and self._flight() > 0):
                break
            chunk = self._take_from_send_buf(budget)
            if not chunk:
                break
            self._emit(self._data_flags(), seq=self.snd_nxt,
                       payload=chunk, now=now, track=True)
            self.snd_nxt = seq_add(self.snd_nxt, len(chunk))
            self._fct_touch(len(chunk), now, inbound=False)
        if self.snd_wnd == 0 and self.send_buf and not self.rtx \
                and self._persist_deadline is None \
                and self.state in (ESTABLISHED, CLOSE_WAIT, FIN_WAIT_1):
            self._persist_interval = self.rto
            self._persist_deadline = now + self._persist_interval
        if self.snd_fin_pending and not self.send_buf \
                and self.fin_seq is None:
            self.fin_seq = self.snd_nxt
            self._emit(TcpFlags.FIN | TcpFlags.ACK, seq=self.snd_nxt,
                       payload=b"", now=now, track=True, is_fin=True)
            self.snd_nxt = seq_add(self.snd_nxt, 1)

    def _take_from_send_buf(self, n: int) -> bytes:
        out = bytearray()
        while n > 0 and self.send_buf:
            chunk = self.send_buf[0]
            if len(chunk) <= n:
                out += chunk
                n -= len(chunk)
                self.send_buf.popleft()
            else:
                out += chunk[:n]
                self.send_buf[0] = chunk[n:]
                n = 0
        self.send_buf_len -= len(out)
        return bytes(out)

    def _data_flags(self) -> int:
        """Flags for a FRESH data segment: ACK|PSH, plus the one-shot
        CWR announcing a pending ECN window cut (RFC 3168 6.1.2 —
        never on retransmissions)."""
        flags = TcpFlags.ACK | TcpFlags.PSH
        if self.ecn_active and self.cwr_pending:
            flags |= TcpFlags.CWR
            self.cwr_pending = False
        return flags

    def _transmit_segment(self, seq: int, payload: bytes, is_fin: bool,
                          now: int) -> None:
        """Retransmission path only — fresh segments go through _emit.
        Karn under timestamps: a retransmitted segment carries a FRESH
        timestamp, so its echo measures the retransmission, never the
        ambiguous original; sampling also pauses during RTO backoff."""
        flags = TcpFlags.ACK
        mss = None
        window_scale = None
        if is_fin:
            flags |= TcpFlags.FIN
        elif payload == b"" and seq == self.iss:
            # Retransmitted SYN / SYN-ACK must carry the same options as
            # the original — window scaling AND the ECN-setup flags —
            # else a lost SYN-ACK leaves the two sides disagreeing.
            flags = TcpFlags.SYN
            mss = MSS
            window_scale = self._wscale_offer
            if self.ecn_on:
                flags |= TcpFlags.ECE | TcpFlags.CWR
            if self.state == SYN_RECEIVED:
                flags = TcpFlags.SYN | TcpFlags.ACK
                if self.ecn_active:
                    flags |= TcpFlags.ECE
                window_scale = (self._wscale_offer if self.our_wscale
                                else None)
        elif payload:
            flags |= TcpFlags.PSH
        if self.ece_latch and not (flags & TcpFlags.SYN):
            flags |= TcpFlags.ECE  # echo until CWR (RFC 3168 6.1.3)
        self.outbox.append((TcpHeader(
            seq=seq, ack=self.rcv_nxt, flags=flags,
            window=self._wire_window(flags), mss=mss,
            window_scale=window_scale,
            sack_blocks=self._sack_blocks(),
            timestamp=now + 1,
            timestamp_echo=self._take_ts_echo()), payload))
        self.segments_sent += 1
        self._note_ack_sent()

    def _take_ts_echo(self) -> int:
        """The echo for an outgoing segment: the last timestamp value
        received, cleared after one use so an outdated echo is never
        resent (ref tcp.c:2433-2434)."""
        ts, self._ts_recent = self._ts_recent, 0
        return ts

    def _emit(self, flags: int, seq: int, payload: bytes, now: int,
              track: bool = False, is_fin: bool = False,
              mss: int | None = None,
              window_scale: int | None = None) -> None:
        if self.ece_latch and not (flags & TcpFlags.SYN):
            flags |= TcpFlags.ECE  # echo until CWR (RFC 3168 6.1.3)
        ack = self.rcv_nxt if (flags & TcpFlags.ACK) else 0
        self.outbox.append((TcpHeader(
            seq=seq, ack=ack, flags=flags, window=self._wire_window(flags),
            mss=mss, window_scale=window_scale,
            timestamp=now + 1,
            timestamp_echo=self._take_ts_echo()), payload))
        self.segments_sent += 1
        if flags & TcpFlags.ACK:
            self._note_ack_sent()
        if track:
            self.rtx.append([seq, payload, is_fin, now, False, False])
            if self.rto_deadline is None:
                self.rto_deadline = now + self.rto

    def _note_ack_sent(self) -> None:
        """Any segment carrying our current rcv_nxt satisfies a pending
        delayed ack (piggybacking)."""
        self._segs_since_ack = 0
        self._delack_deadline = None

    def _emit_ack(self, now: int) -> None:
        flags = TcpFlags.ACK
        if self.ece_latch:
            flags |= TcpFlags.ECE  # echo until CWR (RFC 3168 6.1.3)
        self.outbox.append((TcpHeader(
            seq=self.snd_nxt, ack=self.rcv_nxt, flags=flags,
            window=self._wire_window(TcpFlags.ACK),
            sack_blocks=self._sack_blocks(),
            timestamp=now + 1,
            timestamp_echo=self._take_ts_echo()), b""))
        self.segments_sent += 1
        self._note_ack_sent()
