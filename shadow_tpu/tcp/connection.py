"""TCP connection state machine (sans-I/O).

Covers: three-way handshake (active + passive), bidirectional data
transfer with flow control (advertised windows), reno congestion control
(slow start, congestion avoidance, fast retransmit/recovery on 3 dupacks,
timeout backoff), Jacobson/Karn RTT estimation with integer-ns RTO,
out-of-order reassembly, graceful close through FIN states, TIME_WAIT,
and RST on unexpected segments.

Also modeled: window scaling (RFC 7323, ref window_scaling.rs), SACK
(RFC 2018: receiver reports reassembly runs, sender skips sacked
segments — ref the reference's C tcp.c SACK handling +
tcp_retransmit_tally.cc), MSS clamping from the peer's SYN option, and
a pluggable congestion-control seam with reno as the in-tree algorithm
(ref: tcp_cong.c/tcp_cong_reno.c — the reference likewise ships only
reno behind its ops table).

Deliberate simplifications (documented for parity tracking against the
reference's states.rs/connection.rs): immediate ACKs (no delayed-ACK
timer), no Nagle, no zero-window persist probe. Each is listed in
docs/PARITY.md.

All arithmetic is integer (ns for time, mod-2^32 for sequence space) so
scalar and batched stepping agree bit-for-bit.
"""

from __future__ import annotations

from collections import deque

from shadow_tpu.net.packet import TcpFlags, TcpHeader

# States (ref: src/lib/tcp/src/states.rs explicit state types).
CLOSED = 0
LISTEN = 1
SYN_SENT = 2
SYN_RECEIVED = 3
ESTABLISHED = 4
FIN_WAIT_1 = 5
FIN_WAIT_2 = 6
CLOSING = 7
TIME_WAIT = 8
CLOSE_WAIT = 9
LAST_ACK = 10

STATE_NAMES = {
    CLOSED: "closed", LISTEN: "listen", SYN_SENT: "syn-sent",
    SYN_RECEIVED: "syn-received", ESTABLISHED: "established",
    FIN_WAIT_1: "fin-wait-1", FIN_WAIT_2: "fin-wait-2", CLOSING: "closing",
    TIME_WAIT: "time-wait", CLOSE_WAIT: "close-wait", LAST_ACK: "last-ack",
}

MSS = 1460  # MTU 1500 - 40 header bytes
MAX_WINDOW = 65_535
WINDOW_SCALE = 7                # our advertised shift (RFC 7323 max 14)
MAX_SACK_BLOCKS = 3             # with timestamps elided, 3 fit on wire

INIT_RTO_NS = 1_000_000_000     # RFC 6298 initial
MIN_RTO_NS = 200_000_000        # Linux-style floor
MAX_RTO_NS = 60_000_000_000
TIME_WAIT_NS = 60_000_000_000   # 2 * MSL with MSL=30s
DUPACK_THRESHOLD = 3

_SEQ_MOD = 1 << 32


class RenoCongestion:
    """NewReno ops behind the pluggable seam (ref: tcp_cong.c ops table
    + tcp_cong_reno.c).  Owns cwnd/ssthresh; the connection reports ack
    and loss events."""

    name = "reno"

    def __init__(self):
        self.cwnd = 10 * MSS  # RFC 6928 IW10
        self.ssthresh = 64 * 1024

    def on_new_ack(self, acked: int) -> None:
        if self.cwnd < self.ssthresh:
            self.cwnd += min(acked, MSS)  # slow start
        else:
            self.cwnd += max(1, MSS * MSS // self.cwnd)  # AIMD

    def on_fast_retransmit(self, flight: int) -> None:
        self.ssthresh = max(flight // 2, 2 * MSS)
        self.cwnd = self.ssthresh + 3 * MSS

    def on_recovery_dupack(self) -> None:
        self.cwnd += MSS  # inflation

    def on_exit_recovery(self) -> None:
        self.cwnd = self.ssthresh

    def on_rto(self, flight: int) -> None:
        self.ssthresh = max(flight // 2, 2 * MSS)
        self.cwnd = MSS


CONGESTION_ALGOS = {"reno": RenoCongestion}


def seq_add(a: int, b: int) -> int:
    return (a + b) % _SEQ_MOD


def seq_sub(a: int, b: int) -> int:
    """Signed distance a-b in sequence space."""
    d = (a - b) % _SEQ_MOD
    return d - _SEQ_MOD if d >= _SEQ_MOD // 2 else d


def seq_lt(a: int, b: int) -> bool:
    return seq_sub(a, b) < 0


def seq_leq(a: int, b: int) -> bool:
    return seq_sub(a, b) <= 0


class TcpConnection:
    """One direction-pair of TCP state. Emitted segments accumulate in
    `outbox` as (TcpHeader, payload_bytes); the owner drains it."""

    def __init__(self, iss: int, recv_buf_max: int = 174_760,
                 send_buf_max: int = 131_072, congestion: str = "reno"):
        self.state = CLOSED
        self.iss = iss % _SEQ_MOD

        # Send side.
        self.snd_una = self.iss
        self.snd_nxt = self.iss
        self.snd_wnd = MSS  # until the peer advertises
        self.send_buf: deque = deque()   # byte chunks awaiting segmentation
        self.send_buf_len = 0
        self.send_buf_max = send_buf_max
        self.snd_fin_pending = False     # app closed; FIN after data drains
        self.fin_seq: int | None = None  # seq consumed by our FIN
        # Retransmission queue: list of [seq, payload, is_fin, sent_at,
        # retransmitted, sacked] — ordered by seq.
        self.rtx: list = []

        # Receive side.
        self.irs = 0
        self.rcv_nxt = 0
        self.recv_buf: deque = deque()
        self.recv_buf_len = 0
        self.recv_buf_max = recv_buf_max
        self.reassembly: dict[int, bytes] = {}  # seq -> payload (future)
        self.peer_fin_seq: int | None = None   # set once the FIN is
        self.pending_fin_seq: int | None = None  # ...processed in order

        # Window scaling (RFC 7323; ref window_scaling.rs): we always
        # offer WINDOW_SCALE; active only if the peer's SYN offers too.
        self.our_wscale = 0    # shift applied to windows we advertise
        self.peer_wscale = 0   # shift applied to windows we receive
        self.eff_mss = MSS     # clamped by the peer's MSS option

        # Congestion control behind the pluggable seam (tcp_cong.c).
        self.cong = CONGESTION_ALGOS[congestion]()
        self.dupacks = 0
        self.in_fast_recovery = False
        self.recover = self.iss

        # RTT/RTO (integer ns, Jacobson/Karn). One *timed segment* per
        # window, BSD-style: sampling from arbitrary cleared rtx entries
        # would poison srtt after a retransmission repaired a hole (the
        # cumulative ack clears old segments whose wait includes the
        # whole stall).
        self.srtt = 0
        self.rttvar = 0
        self.rto = INIT_RTO_NS
        self.rto_deadline: int | None = None
        self.time_wait_deadline: int | None = None
        self._timed_end_seq: int | None = None
        self._timed_sent_at = 0

        self.outbox: deque = deque()  # (TcpHeader, payload)
        self.error: str | None = None  # set on RST / fatal
        self.syn_retries = 0

        # Counters for stats/debug.
        self.retransmit_count = 0
        self.segments_sent = 0
        self.segments_received = 0

    # Congestion variables live on the algorithm object; these views
    # keep call sites and tests readable.
    @property
    def cwnd(self) -> int:
        return self.cong.cwnd

    @property
    def ssthresh(self) -> int:
        return self.cong.ssthresh

    # ------------------------------------------------------------------
    # App-side API
    # ------------------------------------------------------------------

    def open_active(self, now: int) -> None:
        """connect(): emit SYN (states.rs Init->SynSent)."""
        assert self.state == CLOSED
        self.state = SYN_SENT
        self._emit(TcpFlags.SYN, seq=self.iss, payload=b"", now=now,
                   track=True)
        self.snd_nxt = seq_add(self.iss, 1)

    def open_passive(self) -> None:
        assert self.state == CLOSED
        self.state = LISTEN

    def send_space(self) -> int:
        return self.send_buf_max - self.send_buf_len

    def write(self, data: bytes, now: int) -> int:
        """Append app data; returns bytes accepted (0 = would block)."""
        if self.state not in (ESTABLISHED, CLOSE_WAIT):
            raise ConnectionError(f"write in {STATE_NAMES[self.state]}")
        if self.snd_fin_pending:
            raise ConnectionError("write after close")
        n = min(len(data), self.send_space())
        if n > 0:
            self.send_buf.append(bytes(data[:n]))
            self.send_buf_len += n
            self._push_data(now)
        return n

    def readable_bytes(self) -> int:
        return self.recv_buf_len

    def at_eof(self) -> bool:
        return (self.peer_fin_seq is not None and self.recv_buf_len == 0
                and not self.reassembly)

    def read(self, n: int, now: int) -> bytes:
        window_before = self._recv_window()
        out = bytearray()
        while n > 0 and self.recv_buf:
            chunk = self.recv_buf[0]
            if len(chunk) <= n:
                out += chunk
                n -= len(chunk)
                self.recv_buf.popleft()
            else:
                out += chunk[:n]
                self.recv_buf[0] = chunk[n:]
                n = 0
        if out:
            self.recv_buf_len -= len(out)
            # Window-update ACK only when the window was pinched shut —
            # an ACK per read() would flood the wire with pure acks.
            if window_before < MSS and self._recv_window() >= MSS and \
                    self.state in (ESTABLISHED, FIN_WAIT_1, FIN_WAIT_2):
                self._emit_ack(now)
        return bytes(out)

    def close(self, now: int) -> None:
        """App close: FIN once the send buffer drains
        (states.rs Established->FinWait1 / CloseWait->LastAck)."""
        if self.state in (CLOSED, LISTEN):
            self.state = CLOSED
            return
        if self.state == SYN_SENT:
            self.state = CLOSED
            self.rto_deadline = None
            self.rtx.clear()
            return
        if self.snd_fin_pending or self.fin_seq is not None:
            return
        self.snd_fin_pending = True
        if self.state == ESTABLISHED:
            self.state = FIN_WAIT_1
        elif self.state == CLOSE_WAIT:
            self.state = LAST_ACK
        self._push_data(now)

    def abort(self, now: int) -> None:
        """RST out, state torn down."""
        if self.state not in (CLOSED, LISTEN, TIME_WAIT):
            self._emit(TcpFlags.RST | TcpFlags.ACK, seq=self.snd_nxt,
                       payload=b"", now=now)
        self.state = CLOSED
        self.error = self.error or "aborted"
        self.rto_deadline = None

    # ------------------------------------------------------------------
    # Timers
    # ------------------------------------------------------------------

    def next_timer_expiry(self) -> int | None:
        candidates = [t for t in (self.rto_deadline,
                                  self.time_wait_deadline) if t is not None]
        return min(candidates) if candidates else None

    def on_timer(self, now: int) -> None:
        if self.time_wait_deadline is not None \
                and now >= self.time_wait_deadline:
            self.time_wait_deadline = None
            if self.state == TIME_WAIT:
                self.state = CLOSED
        if self.rto_deadline is not None and now >= self.rto_deadline:
            self._on_rto(now)

    def _on_rto(self, now: int) -> None:
        """Retransmission timeout (RFC 6298 5.4-5.7 + reno reset)."""
        if not self.rtx:
            self.rto_deadline = None
            return
        # Handshake gives up after 6 backoffs (Linux tcp_syn_retries):
        # connecting to a dead/closed port must fail, not hang forever.
        if self.state in (SYN_SENT, SYN_RECEIVED):
            self.syn_retries += 1
            if self.syn_retries > 6:
                self.error = "connection timed out"
                self.state = CLOSED
                self.rto_deadline = None
                self.rtx.clear()
                return
        flight = seq_sub(self.snd_nxt, self.snd_una)
        self.cong.on_rto(flight)
        self.dupacks = 0
        self.in_fast_recovery = False
        self.rto = min(self.rto * 2, MAX_RTO_NS)
        seg = self.rtx[0]
        seg[3] = now
        seg[4] = True  # Karn: no RTT sample from retransmits
        self.retransmit_count += 1
        self._transmit_segment(seg[0], seg[1], seg[2], now)
        self.rto_deadline = now + self.rto

    # ------------------------------------------------------------------
    # Packet ingress
    # ------------------------------------------------------------------

    def on_packet(self, hdr: TcpHeader, payload: bytes, now: int) -> None:
        self.segments_received += 1
        if self.state == CLOSED:
            return
        if hdr.flags & TcpFlags.RST:
            self._on_rst(hdr)
            return
        if self.state == LISTEN:
            # Owner (listener socket) is responsible for spawning child
            # connections; a LISTEN connection itself ignores non-SYN.
            return
        if self.state == SYN_SENT:
            self._on_packet_syn_sent(hdr, now)
            return
        # --- synchronized states ---
        if hdr.flags & TcpFlags.SYN:
            # Re-sent SYN (our SYN-ACK was lost): re-ACK it.
            if self.state == SYN_RECEIVED and hdr.seq == seq_sub(
                    self.rcv_nxt, 1) % _SEQ_MOD:
                self._emit_synack(now)
                return
            self._emit_ack(now)
            return
        if not (hdr.flags & TcpFlags.ACK):
            return
        self._on_ack(hdr, now, is_pure_ack=not payload
                     and not (hdr.flags & TcpFlags.FIN))
        if payload:
            self._on_data(hdr.seq, payload, now)
        if hdr.flags & TcpFlags.FIN:
            self._on_fin(hdr, payload, now)

    def accept_syn(self, hdr: TcpHeader, now: int) -> None:
        """Passive open: called on a child connection created by a
        listener for an incoming SYN."""
        assert self.state in (CLOSED, LISTEN)
        self.irs = hdr.seq
        self.rcv_nxt = seq_add(hdr.seq, 1)
        self.snd_wnd = hdr.window
        self.state = SYN_RECEIVED
        self._emit_synack(now)
        self.snd_nxt = seq_add(self.iss, 1)

    def _emit_synack(self, now: int) -> None:
        self._emit(TcpFlags.SYN | TcpFlags.ACK, seq=self.iss, payload=b"",
                   now=now, track=(self.snd_nxt == self.iss))

    def _on_packet_syn_sent(self, hdr: TcpHeader, now: int) -> None:
        if (hdr.flags & (TcpFlags.SYN | TcpFlags.ACK)) == \
                (TcpFlags.SYN | TcpFlags.ACK):
            if hdr.ack != self.snd_nxt:
                self.abort(now)
                return
            self.irs = hdr.seq
            self.rcv_nxt = seq_add(hdr.seq, 1)
            self.snd_una = hdr.ack
            self.snd_wnd = hdr.window
            self._clear_acked(now)
            self.state = ESTABLISHED
            self._emit_ack(now)
        elif hdr.flags & TcpFlags.SYN:
            # Simultaneous open: not modeled; reset.
            self.abort(now)

    def _on_rst(self, hdr: TcpHeader) -> None:
        self.error = "connection reset"
        self.state = CLOSED
        self.rto_deadline = None
        self.time_wait_deadline = None

    def _on_ack(self, hdr: TcpHeader, now: int,
                is_pure_ack: bool = True) -> None:
        ack = hdr.ack
        if seq_lt(self.snd_nxt, ack):
            # Acks something we never sent.
            self._emit_ack(now)
            return
        window_changed = hdr.window != self.snd_wnd
        self.snd_wnd = hdr.window
        if seq_lt(self.snd_una, ack):
            self._handle_new_ack(ack, now)
        elif ack == self.snd_una and self.rtx and is_pure_ack \
                and not window_changed:
            # RFC 5681: only payload-free, window-unchanged acks count as
            # duplicates — a peer streaming its own data repeats our ack
            # number without implying loss.
            self._handle_dupack(now)
        # Handshake completion for passive side.
        if self.state == SYN_RECEIVED and seq_lt(self.iss, ack):
            self.state = ESTABLISHED
        self._advance_close_states(now)
        self._push_data(now)

    def _handle_new_ack(self, ack: int, now: int) -> None:
        acked = seq_sub(ack, self.snd_una)
        self.snd_una = ack
        self.dupacks = 0
        sample = self._clear_acked(now)
        if sample is not None:
            self._update_rtt(sample)
        elif self.srtt > 0:
            # Forward progress undoes exponential RTO backoff even when
            # Karn's rule yields no sample (the ack was for a retransmit).
            # Without this, sustained loss walks rto to the 60s cap and
            # every remaining hole costs a full max-RTO — transfers that
            # should take seconds take hours.
            self.rto = min(max(self.srtt + max(4 * self.rttvar, 1_000_000),
                               MIN_RTO_NS), MAX_RTO_NS)
        if self.in_fast_recovery:
            if seq_lt(self.recover, ack) or ack == self.recover:
                self.in_fast_recovery = False
                self.cong.on_exit_recovery()
            else:
                # Partial ack: retransmit next hole immediately.
                if self.rtx:
                    seg = self.rtx[0]
                    seg[3] = now
                    seg[4] = True
                    self.retransmit_count += 1
                    self._transmit_segment(seg[0], seg[1], seg[2], now)
        else:
            self.cong.on_new_ack(acked)
        # RTO restart (RFC 6298 5.3).
        self.rto_deadline = (now + self.rto) if self.rtx else None

    def _handle_dupack(self, now: int) -> None:
        self.dupacks += 1
        if self.in_fast_recovery:
            self.cong.on_recovery_dupack()
            self._push_data(now)
        elif self.dupacks == DUPACK_THRESHOLD:
            flight = seq_sub(self.snd_nxt, self.snd_una)
            self.cong.on_fast_retransmit(flight)
            self.in_fast_recovery = True
            self.recover = self.snd_nxt
            if self.rtx:
                seg = self.rtx[0]
                seg[3] = now
                seg[4] = True
                self.retransmit_count += 1
                self._transmit_segment(seg[0], seg[1], seg[2], now)

    def _clear_acked(self, now: int):
        """Drop fully-acked segments from the rtx queue; returns the RTT
        sample (ns) if the ack covers the timed segment, else None."""
        while self.rtx:
            seq, payload, is_fin, sent_at, retransmitted = self.rtx[0]
            # Sequence space consumed: data bytes, or 1 for SYN/FIN.
            end = seq_add(seq, len(payload) + (1 if is_fin else 0)
                          + (1 if payload == b"" and not is_fin else 0))
            if seq_leq(end, self.snd_una):
                self.rtx.pop(0)
            else:
                break
        if self._timed_end_seq is not None \
                and seq_leq(self._timed_end_seq, self.snd_una):
            sample = now - self._timed_sent_at
            self._timed_end_seq = None
            return sample
        return None

    def _update_rtt(self, sample: int) -> None:
        if sample <= 0:
            sample = 1
        if self.srtt == 0:
            self.srtt = sample
            self.rttvar = sample // 2
        else:
            err = abs(self.srtt - sample)
            self.rttvar = (3 * self.rttvar + err) // 4
            self.srtt = (7 * self.srtt + sample) // 8
        self.rto = self.srtt + max(4 * self.rttvar, 1_000_000)
        self.rto = min(max(self.rto, MIN_RTO_NS), MAX_RTO_NS)

    # ------------------------------------------------------------------
    # Data ingress / reassembly
    # ------------------------------------------------------------------

    def _recv_window(self) -> int:
        return min(MAX_WINDOW, max(0, self.recv_buf_max - self.recv_buf_len))

    def _on_data(self, seq: int, payload: bytes, now: int) -> None:
        if self.state not in (ESTABLISHED, FIN_WAIT_1, FIN_WAIT_2):
            return
        # Trim anything already received.
        offset = seq_sub(self.rcv_nxt, seq)
        if offset >= len(payload):
            self._emit_ack(now)  # pure duplicate
            return
        if offset > 0:
            payload = payload[offset:]
            seq = self.rcv_nxt
        if seq != self.rcv_nxt:
            # Future segment: stash (bounded by the advertised window).
            if seq_sub(seq, self.rcv_nxt) < self.recv_buf_max:
                self.reassembly.setdefault(seq, payload)
            self._emit_ack(now)  # dupack → sender fast-retransmits
            return
        # In-order: deliver, then drain any contiguous stashed segments.
        self._deliver(payload)
        while self.rcv_nxt in self.reassembly:
            self._deliver(self.reassembly.pop(self.rcv_nxt))
        # An out-of-order FIN becomes processable once the gap fills.
        if self.pending_fin_seq == self.rcv_nxt:
            self._process_fin(now)
        self._emit_ack(now)

    def _deliver(self, payload: bytes) -> None:
        space = self.recv_buf_max - self.recv_buf_len
        take = payload[:space]
        if take:
            self.recv_buf.append(take)
            self.recv_buf_len += len(take)
            self.rcv_nxt = seq_add(self.rcv_nxt, len(take))
        # Bytes beyond buffer space are NOT acked; the shrunken advertised
        # window tells the sender to back off and retransmit later.

    def _on_fin(self, hdr: TcpHeader, payload: bytes, now: int) -> None:
        if self.peer_fin_seq is not None:
            # Retransmitted FIN (our ACK was lost, e.g. in TIME_WAIT):
            # just re-ACK.
            self._emit_ack(now)
            return
        fin_seq = seq_add(hdr.seq, len(payload))
        if fin_seq != self.rcv_nxt:
            # FIN beyond data we haven't received: wait for reassembly.
            self.pending_fin_seq = fin_seq
            self._emit_ack(now)
            return
        self._process_fin(now)
        self._emit_ack(now)

    def _process_fin(self, now: int) -> None:
        self.peer_fin_seq = self.rcv_nxt
        self.pending_fin_seq = None
        self.rcv_nxt = seq_add(self.rcv_nxt, 1)
        if self.state == ESTABLISHED:
            self.state = CLOSE_WAIT
        elif self.state == FIN_WAIT_1:
            self.state = CLOSING
        elif self.state == FIN_WAIT_2:
            self._enter_time_wait(now)
        self._advance_close_states(now)

    def _advance_close_states(self, now: int) -> None:
        fin_acked = (self.fin_seq is not None
                     and seq_lt(self.fin_seq, self.snd_una))
        if self.state == FIN_WAIT_1 and fin_acked:
            self.state = FIN_WAIT_2
        elif self.state == CLOSING and fin_acked:
            self._enter_time_wait(now)
        elif self.state == LAST_ACK and fin_acked:
            self.state = CLOSED
            self.rto_deadline = None

    def _enter_time_wait(self, now: int) -> None:
        self.state = TIME_WAIT
        self.rto_deadline = None
        self.time_wait_deadline = now + TIME_WAIT_NS

    # ------------------------------------------------------------------
    # Segment egress
    # ------------------------------------------------------------------

    def _flight(self) -> int:
        return seq_sub(self.snd_nxt, self.snd_una)

    def _push_data(self, now: int) -> None:
        """Segmentize send_buf within min(cwnd, peer window)."""
        if self.state not in (ESTABLISHED, CLOSE_WAIT, FIN_WAIT_1,
                              CLOSING, LAST_ACK):
            return
        window = min(self.cwnd, self.snd_wnd)
        while self.send_buf and self._flight() < window:
            budget = min(window - self._flight(), MSS)
            chunk = self._take_from_send_buf(budget)
            if not chunk:
                break
            self._emit(TcpFlags.ACK | TcpFlags.PSH, seq=self.snd_nxt,
                       payload=chunk, now=now, track=True)
            self.snd_nxt = seq_add(self.snd_nxt, len(chunk))
        if self.snd_fin_pending and not self.send_buf \
                and self.fin_seq is None:
            self.fin_seq = self.snd_nxt
            self._emit(TcpFlags.FIN | TcpFlags.ACK, seq=self.snd_nxt,
                       payload=b"", now=now, track=True, is_fin=True)
            self.snd_nxt = seq_add(self.snd_nxt, 1)

    def _take_from_send_buf(self, n: int) -> bytes:
        out = bytearray()
        while n > 0 and self.send_buf:
            chunk = self.send_buf[0]
            if len(chunk) <= n:
                out += chunk
                n -= len(chunk)
                self.send_buf.popleft()
            else:
                out += chunk[:n]
                self.send_buf[0] = chunk[n:]
                n = 0
        self.send_buf_len -= len(out)
        return bytes(out)

    def _transmit_segment(self, seq: int, payload: bytes, is_fin: bool,
                          now: int) -> None:
        """Retransmission path only — fresh segments go through _emit."""
        # Karn: a retransmission in the window invalidates the timed
        # segment (its eventual ack is ambiguous).
        self._timed_end_seq = None
        flags = TcpFlags.ACK
        if is_fin:
            flags |= TcpFlags.FIN
        elif payload == b"" and seq == self.iss:
            flags = TcpFlags.SYN  # retransmitted SYN
            if self.state == SYN_RECEIVED:
                flags = TcpFlags.SYN | TcpFlags.ACK
        elif payload:
            flags |= TcpFlags.PSH
        self.outbox.append((TcpHeader(
            seq=seq, ack=self.rcv_nxt, flags=flags,
            window=self._recv_window()), payload))
        self.segments_sent += 1

    def _emit(self, flags: int, seq: int, payload: bytes, now: int,
              track: bool = False, is_fin: bool = False) -> None:
        ack = self.rcv_nxt if (flags & TcpFlags.ACK) else 0
        self.outbox.append((TcpHeader(
            seq=seq, ack=ack, flags=flags, window=self._recv_window()),
            payload))
        self.segments_sent += 1
        if track:
            self.rtx.append([seq, payload, is_fin, now, False])
            if self.rto_deadline is None:
                self.rto_deadline = now + self.rto
            if self._timed_end_seq is None:
                self._timed_end_seq = seq_add(
                    seq, len(payload) + (1 if is_fin else 0)
                    + (1 if payload == b"" and not is_fin else 0))
                self._timed_sent_at = now

    def _emit_ack(self, now: int) -> None:
        self.outbox.append((TcpHeader(
            seq=self.snd_nxt, ack=self.rcv_nxt, flags=TcpFlags.ACK,
            window=self._recv_window()), b""))
        self.segments_sent += 1
