"""Config-construction helpers — the shadowtools equivalent.

Ref: shadowtools/src/shadowtools/config.py — typed helpers for building
simulation configs programmatically.  TypedDicts are plain dicts at
runtime (feed them straight to `ConfigOptions.from_dict` or dump with
yaml), while letting mypy/pyright check call sites.
"""

from __future__ import annotations

from typing import Dict, List, TypedDict, Union


class Graph(TypedDict, total=False):
    type: str            # "gml" or a builtin like "1_gbit_switch"
    inline: str
    file: Dict[str, str]  # {"path": ...}


class Network(TypedDict, total=False):
    graph: Graph
    use_shortest_path: bool


class General(TypedDict, total=False):
    stop_time: Union[str, int]
    seed: int
    parallelism: int
    bootstrap_end_time: Union[str, int]
    data_directory: str
    progress: bool
    heartbeat_interval: Union[str, int]


class Process(TypedDict, total=False):
    path: str
    args: List[str]
    environment: Dict[str, str]
    start_time: Union[str, int]
    shutdown_time: Union[str, int]
    expected_final_state: str


class Host(TypedDict, total=False):
    network_node_id: int
    ip_addr: str
    bandwidth_down: Union[str, int]
    bandwidth_up: Union[str, int]
    pcap_enabled: bool
    processes: List[Process]


class Experimental(TypedDict, total=False):
    scheduler: str
    runahead: Union[str, int]
    use_dynamic_runahead: bool
    interface_qdisc: str
    strace_logging_mode: str
    socket_send_buffer: int
    socket_recv_buffer: int
    use_cpu_pinning: bool
    use_perf_timers: bool
    tpu_max_packets_per_round: int
    tpu_min_device_batch: int


class Config(TypedDict, total=False):
    general: General
    network: Network
    experimental: Experimental
    hosts: Dict[str, Host]


def one_host_config(path: str, args: List[str] | None = None,
                    stop_time: str = "1h",
                    environment: Dict[str, str] | None = None,
                    seed: int = 1) -> Config:
    """A single host on a 1 Gbit switch running one process — the shape
    `shadow-exec` uses (ref: shadowtools/shadow_exec.py)."""
    return Config(
        general=General(stop_time=stop_time, seed=seed),
        network=Network(graph=Graph(type="1_gbit_switch")),
        hosts={
            "host": Host(
                network_node_id=0,
                processes=[Process(path=path, args=list(args or []),
                                   environment=dict(environment or {}),
                                   expected_final_state="any")],
            )
        },
    )
