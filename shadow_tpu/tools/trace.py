"""Flight-recorder CLI: summarize, attribute, export.

    python -m shadow_tpu.tools.trace DATA_DIR            # summarize
    python -m shadow_tpu.tools.trace DATA_DIR --chrome out.json
    python -m shadow_tpu.tools.trace --run sim.yaml      # run + summarize
    python -m shadow_tpu.tools.trace --smoke [--hosts N] # CI smoke

Reads the artifacts a flight-recorded run leaves in its data
directory (`sim-stats.json`, `flight-sim.bin`, `flight-wall.json` —
docs/OBSERVABILITY.md) and prints:

- the sim-time channel summary (records, spans by family, aborts),
- the device-eligibility attribution report (one reason code per
  conservative round; the counts always sum to the round total),
- the wall-time phase breakdown (export/convert/compile/execute/
  import/barrier/host-loop),

and exports Chrome trace-event JSON (--chrome) that loads in Perfetto
with rounds, spans, and phases as nested slices.

`--run` executes a config with the flight recorder forced on and then
summarizes its data directory.  `--smoke` builds a small tgen TCP
tier (tools/netgen), runs it traced, and exits non-zero unless the
summary renders and the eligibility report accounts for 100% of
rounds — the `./setup trace` target.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load(data_dir: str):
    stats_path = os.path.join(data_dir, "sim-stats.json")
    if not os.path.exists(stats_path):
        raise FileNotFoundError(
            f"{stats_path} not found — not a simulation data dir?")
    with open(stats_path) as f:
        stats = json.load(f)
    sim_bytes = b""
    sim_path = os.path.join(data_dir, "flight-sim.bin")
    if os.path.exists(sim_path):
        with open(sim_path, "rb") as f:
            sim_bytes = f.read()
    wall = None
    wall_path = os.path.join(data_dir, "flight-wall.json")
    if os.path.exists(wall_path):
        with open(wall_path) as f:
            wall = json.load(f)
    return stats, sim_bytes, wall


def summarize(data_dir: str, chrome_out: str | None = None,
              out=sys.stdout) -> bool:
    """Print the trace summary + eligibility report; write the Chrome
    export when asked.  Returns True when the eligibility counts
    account for 100% of rounds."""
    from shadow_tpu.trace.audit import render_report
    from shadow_tpu.trace.events import (FLIGHT_REC_BYTES, FR_ROUND,
                                         FR_SPAN_ABORT, FR_SPAN_COMMIT,
                                         FR_SPAN_START, iter_records)

    stats, sim_bytes, wall = _load(data_dir)
    rounds = stats.get("rounds", 0)
    metrics = stats.get("metrics", {})
    elig = metrics.get("wall", {}).get("eligibility", {})

    print(f"trace summary for {data_dir}", file=out)
    print(f"  rounds {rounds}, packets {stats.get('packets_sent', 0)}, "
          f"events {stats.get('events', 0)}, sim end "
          f"{stats.get('end_time_ns', 0) / 1e9:.3f}s", file=out)

    if sim_bytes:
        kinds = {FR_ROUND: 0, FR_SPAN_START: 0, FR_SPAN_COMMIT: 0,
                 FR_SPAN_ABORT: 0}
        span_rounds = 0
        for _t, kind, _a, _b, c in iter_records(sim_bytes):
            kinds[kind] = kinds.get(kind, 0) + 1
            if kind == FR_SPAN_COMMIT:
                span_rounds += c
        n_recs = len(sim_bytes) // FLIGHT_REC_BYTES
        print(f"  sim-time channel: {n_recs} records "
              f"({kinds[FR_ROUND]} round, {kinds[FR_SPAN_COMMIT]} span "
              f"commits covering {span_rounds} rounds, "
              f"{kinds[FR_SPAN_ABORT]} aborts)", file=out)
    else:
        print("  sim-time channel: absent (run with "
              "experimental.flight_recorder: on)", file=out)

    ok = bool(elig) and sum(elig.values()) == rounds
    if elig:
        print(render_report(elig, rounds), file=out)
    else:
        print("  (no eligibility block in sim-stats.json — pre-trace "
              "artifact?)", file=out)

    phases = metrics.get("wall", {}).get("phases")
    if phases:
        print("wall-time phases:", file=out)
        for name, ns in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"  {name:<16} {ns / 1e9:10.3f}s", file=out)

    if chrome_out is not None:
        from shadow_tpu.trace.chrome import chrome_trace
        doc = chrome_trace(sim_bytes, wall)
        with open(chrome_out, "w") as f:
            json.dump(doc, f)
        print(f"chrome trace: {chrome_out} "
              f"({len(doc['traceEvents'])} events — load in Perfetto "
              f"or chrome://tracing)", file=out)
    return ok


def run_config(config_path: str, data_dir: str | None = None) -> str:
    """Run a YAML config with the flight recorder forced on; returns
    the data directory."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation

    config = ConfigOptions.from_file(config_path)
    config.experimental.flight_recorder = "on"
    if data_dir is not None:
        config.general.data_directory = data_dir
    _manager, summary = run_simulation(config, write_data=True)
    if not summary.ok:
        for err in summary.plugin_errors:
            print(f"[trace] plugin error: {err}", file=sys.stderr)
    return config.general.data_directory


def smoke(n_hosts: int) -> int:
    """50-host traced tgen TCP tier: summary + eligibility must
    render and account for every round (the ./setup trace target)."""
    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation
    from shadow_tpu.tools.netgen import tcp_stream_yaml

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "trace-smoke")
        # Default nbytes keeps every client mid-stream at stop_time
        # (the generator's expected_final_state is `running`).
        text = tcp_stream_yaml(n_hosts, loss=0.005, stop_time="2s",
                               seed=11, scheduler="tpu")
        config = ConfigOptions.from_yaml_text(text)
        config.experimental.flight_recorder = "on"
        config.general.data_directory = base
        _manager, summary = run_simulation(config, write_data=True)
        if not summary.ok:
            print(f"trace smoke: sim failed: {summary.plugin_errors}",
                  file=sys.stderr)
            return 1
        chrome_out = os.path.join(base, "chrome-trace.json")
        ok = summarize(base, chrome_out=chrome_out)
        if not ok:
            print("trace smoke: eligibility report did not account "
                  "for all rounds", file=sys.stderr)
            return 1
        with open(chrome_out) as f:
            doc = json.load(f)
        slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        if not slices:
            print("trace smoke: chrome export has no slices",
                  file=sys.stderr)
            return 1
    print(f"trace smoke: ok ({n_hosts} hosts, {summary.rounds} rounds "
          f"fully attributed)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow_tpu.tools.trace",
                                 description=__doc__)
    ap.add_argument("data_dir", nargs="?",
                    help="data directory of a flight-recorded run")
    ap.add_argument("--run", metavar="CONFIG",
                    help="run this YAML config with the flight "
                         "recorder on, then summarize")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the 50-host traced smoke sim and exit "
                         "nonzero unless the report renders")
    ap.add_argument("--hosts", type=int, default=50,
                    help="host count for --smoke (default 50)")
    args = ap.parse_args(argv)

    from shadow_tpu.utils.platform import honor_platform_env
    honor_platform_env()

    if args.smoke:
        return smoke(args.hosts)
    if args.run is not None:
        data_dir = run_config(args.run, args.data_dir)
    elif args.data_dir is not None:
        data_dir = args.data_dir
    else:
        ap.print_usage(sys.stderr)
        print("trace: a data directory, --run, or --smoke is required",
              file=sys.stderr)
        return 2
    ok = summarize(data_dir, chrome_out=args.chrome)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
