"""Flight-recorder & sim-netstat CLI: summarize, attribute, export.

    python -m shadow_tpu.tools.trace DATA_DIR            # summarize
    python -m shadow_tpu.tools.trace DATA_DIR --chrome out.json
    python -m shadow_tpu.tools.trace net DATA_DIR        # TCP report
    python -m shadow_tpu.tools.trace explain DATA_DIR    # remediation
    python -m shadow_tpu.tools.trace --run sim.yaml      # run + summarize
    python -m shadow_tpu.tools.trace --smoke [--hosts N] # CI smoke

`net` prints the sim-netstat report: the drop-attribution table with
its conservation check (per-cause counters must sum to the sim's
packets_dropped) and a top-N per-connection table (retransmits, final
srtt/cwnd, buffer peaks) from telemetry-sim.bin.  `explain` maps the
eligibility audit's top blockers to concrete remediation hints (which
hosts force the object path and why, which knobs re-enable spans).

Reads the artifacts a flight-recorded run leaves in its data
directory (`sim-stats.json`, `flight-sim.bin`, `flight-wall.json` —
docs/OBSERVABILITY.md) and prints:

- the sim-time channel summary (records, spans by family, aborts),
- the device-eligibility attribution report (one reason code per
  conservative round; the counts always sum to the round total),
- the wall-time phase breakdown (export/convert/compile/execute/
  import/barrier/host-loop),

and exports Chrome trace-event JSON (--chrome) that loads in Perfetto
with rounds, spans, and phases as nested slices.

`--run` executes a config with the flight recorder forced on and then
summarizes its data directory.  `--smoke` builds a small tgen TCP
tier (tools/netgen), runs it traced, and exits non-zero unless the
summary renders and the eligibility report accounts for 100% of
rounds — the `./setup trace` target.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load(data_dir: str):
    stats_path = os.path.join(data_dir, "sim-stats.json")
    if not os.path.exists(stats_path):
        raise FileNotFoundError(
            f"{stats_path} not found — not a simulation data dir?")
    with open(stats_path) as f:
        stats = json.load(f)
    sim_bytes = b""
    sim_path = os.path.join(data_dir, "flight-sim.bin")
    if os.path.exists(sim_path):
        with open(sim_path, "rb") as f:
            sim_bytes = f.read()
    wall = None
    wall_path = os.path.join(data_dir, "flight-wall.json")
    if os.path.exists(wall_path):
        with open(wall_path) as f:
            wall = json.load(f)
    tel_bytes = b""
    tel_path = os.path.join(data_dir, "telemetry-sim.bin")
    if os.path.exists(tel_path):
        with open(tel_path, "rb") as f:
            tel_bytes = f.read()
    return stats, sim_bytes, wall, tel_bytes


def summarize(data_dir: str, chrome_out: str | None = None,
              out=None) -> bool:
    """Print the trace summary + eligibility report; write the Chrome
    export when asked.  Returns True when the eligibility counts
    account for 100% of rounds."""
    if out is None:
        out = sys.stdout  # resolved at call time (pytest capsys swaps it)
    from shadow_tpu.trace.audit import render_report
    from shadow_tpu.trace.events import (FLIGHT_REC_BYTES, FR_ROUND,
                                         FR_SPAN_ABORT, FR_SPAN_COMMIT,
                                         FR_SPAN_START, iter_records)

    stats, sim_bytes, wall, tel_bytes = _load(data_dir)
    rounds = stats.get("rounds", 0)
    metrics = stats.get("metrics", {})
    elig = metrics.get("wall", {}).get("eligibility", {})

    print(f"trace summary for {data_dir}", file=out)
    print(f"  rounds {rounds}, packets {stats.get('packets_sent', 0)}, "
          f"events {stats.get('events', 0)}, sim end "
          f"{stats.get('end_time_ns', 0) / 1e9:.3f}s", file=out)

    if sim_bytes:
        kinds = {FR_ROUND: 0, FR_SPAN_START: 0, FR_SPAN_COMMIT: 0,
                 FR_SPAN_ABORT: 0}
        span_rounds = 0
        for _t, kind, _a, _b, c in iter_records(sim_bytes):
            kinds[kind] = kinds.get(kind, 0) + 1
            if kind == FR_SPAN_COMMIT:
                span_rounds += c
        n_recs = len(sim_bytes) // FLIGHT_REC_BYTES
        print(f"  sim-time channel: {n_recs} records "
              f"({kinds[FR_ROUND]} round, {kinds[FR_SPAN_COMMIT]} span "
              f"commits covering {span_rounds} rounds, "
              f"{kinds[FR_SPAN_ABORT]} aborts)", file=out)
    else:
        print("  sim-time channel: absent (run with "
              "experimental.flight_recorder: on)", file=out)

    ok = bool(elig) and sum(elig.values()) == rounds
    if elig:
        print(render_report(elig, rounds), file=out)
    else:
        print("  (no eligibility block in sim-stats.json — pre-trace "
              "artifact?)", file=out)

    phases = metrics.get("wall", {}).get("phases")
    if phases:
        print("wall-time phases:", file=out)
        for name, ns in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"  {name:<16} {ns / 1e9:10.3f}s", file=out)

    if chrome_out is not None:
        from shadow_tpu.trace.chrome import chrome_trace
        doc = chrome_trace(sim_bytes, wall, tel_bytes)
        with open(chrome_out, "w") as f:
            json.dump(doc, f)
        print(f"chrome trace: {chrome_out} "
              f"({len(doc['traceEvents'])} events — load in Perfetto "
              f"or chrome://tracing)", file=out)
    return ok


def drop_report(stats: dict, out=None) -> bool:
    """The drop-attribution table + conservation check.  Returns True
    when every wire drop is attributed and the causes sum exactly to
    packets_dropped."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.trace.events import TEL_NAMES, TEL_WIRE_N

    drops = stats.get("metrics", {}).get("sim", {}).get(
        "netstat", {}).get("drops", {})
    total = stats.get("packets_dropped", 0)
    wire = set(TEL_NAMES[:TEL_WIRE_N])
    print("packet-drop attribution (one cause per drop):", file=out)
    wire_sum = 0
    width = max([len(k) for k in drops] + [16])
    for name, n in sorted(drops.items(), key=lambda kv: -kv[1]):
        kind = "wire" if name in wire else (
            "tcp-discard" if name != "unattributed" else "GAP")
        print(f"  {name:<{width}}  {n:>10}  [{kind}]", file=out)
        if name in wire:
            wire_sum += n
    ok = wire_sum == total and "unattributed" not in drops
    if ok:
        print(f"  {'total (wire)':<{width}}  {wire_sum:>10}  "
              f"== packets_dropped ({total}): conserved", file=out)
    else:
        print(f"  total (wire) {wire_sum} != packets_dropped {total} "
              f"— ATTRIBUTION GAP", file=out)
    return ok


def net_report(data_dir: str, top_n: int = 10, out=None) -> bool:
    """`trace net`: drop attribution + the top-N connection table
    from telemetry-sim.bin.  Returns the conservation verdict."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.net.graph import format_ip
    from shadow_tpu.trace.events import TEL_REC_BYTES
    from shadow_tpu.trace.netstat import (group_by_conn,
                                          top_by_retransmits)

    stats, _sim, _wall, tel_bytes = _load(data_dir)
    ok = drop_report(stats, out=out)

    if not tel_bytes:
        print("sim-netstat channel: absent (run with "
              "experimental.sim_netstat: on)", file=out)
        return ok
    by_conn = group_by_conn(tel_bytes)
    n_recs = len(tel_bytes) // TEL_REC_BYTES
    print(f"sim-netstat: {n_recs} samples over {len(by_conn)} "
          f"connections", file=out)
    ranked = top_by_retransmits(by_conn, top_n)
    print(f"top {len(ranked)} connections by retransmits:", file=out)
    print(f"  {'connection':<32} {'rtx':>6} {'sack':>5} "
          f"{'srtt ms':>8} {'cwnd kB':>8} {'sndbuf':>8} "
          f"{'rcvbuf':>8}", file=out)
    for key in ranked:
        host, lport, rport, rip = key
        recs = by_conn[key]
        last = recs[-1]
        name = f"h{host}:{lport}->{format_ip(rip)}:{rport}"
        print(f"  {name:<32} {last[13]:>6} {last[14]:>5} "
              f"{last[8] / 1e6:>8.2f} {last[6] / 1024:>8.1f} "
              f"{max(r[11] for r in recs):>8} "
              f"{max(r[12] for r in recs):>8}", file=out)
    return ok


# Eligibility-blocker remediation hints (`trace explain`), keyed by
# the EL_NAMES the audit reports.  {hosts} interpolates the offending
# host list where the processed config identifies one.
_EXPLAIN = {
    "object-path:pcap": (
        "pcap capture pins these hosts to the Python object path: "
        "{hosts}.  Disable pcap_enabled on them (or accept per-round "
        "spans capped at experimental.pcap_span_cap).",),
    "object-path:cpu-model": (
        "the host CPU model (experimental.host_cpu_threshold) forces "
        "the object path: {hosts}.  Unset it to let these hosts join "
        "engine/device spans.",),
    "object-path:py-task": (
        "engine hosts briefly carried Python-side work (process "
        "spawn/shutdown tasks); normal at sim start and end.",),
    "object-path:other": (
        "a host config (e.g. strace_logging_mode) keeps these hosts "
        "off the native plane: {hosts}.",),
    "engine-span:device-off": (
        "device spans are disabled (experimental.tpu_device_spans: "
        "off); set it to auto or force.",),
    "engine-span:ineligible-family": (
        "no device-span family fits this sim's shape — the PHOLD "
        "family needs pure udp-mesh/phold apps, the TCP family needs "
        "the tgen steady-stream tier (netgen.tcp_stream_yaml).",),
    "engine-span:transient": (
        "the sim was transiently outside the TCP family's modelled "
        "domain (handshake/close stretches); steady-state rounds "
        "still reach the device.",),
    "engine-span:abort-rollback": (
        "device spans aborted (capacity or domain); see dispatch."
        "device_span_*.aborts and grow the runner caps if persistent.",),
    "engine-span:cold-budget": (
        "the device compile budget was not yet earned (1% of wall); "
        "longer runs probe and route automatically.",),
    "engine-span:routed": (
        "the router measured the C++ span faster than the device at "
        "this scale — expected on small sims or CPU backends.",),
    "engine-span:py-limit": (
        "spans were capped before windows could touch an object-path "
        "host; reduce object-path hosts to lengthen spans.",),
    "per-round:forced-device": (
        "forced-device audit mode (tpu_min_device_batch <= 0) runs "
        "every round through the jitted kernel by design.",),
    "per-round:scheduler": (
        "this scheduler has no span path; use scheduler: tpu for "
        "engine/device spans.",),
    "per-round:callback-host": (
        "a host can fire Python callbacks mid-event (Python-owned "
        "sockets), which excludes the whole sim from C++ spans.",),
}


def explain_report(data_dir: str, out=None) -> bool:
    """`trace explain`: top eligibility blockers -> remediation."""
    if out is None:
        out = sys.stdout
    stats, _sim, _wall, _tel = _load(data_dir)
    elig = stats.get("metrics", {}).get("wall", {}).get(
        "eligibility", {})
    rounds = stats.get("rounds", 0)
    if not elig:
        print("no eligibility block in sim-stats.json (pre-trace "
              "artifact?)", file=out)
        return False

    # Offending hosts per object-path cause, from the processed
    # config written next to sim-stats.json.
    pcap_hosts, cpu_hosts, other_hosts = [], [], []
    cfg_path = os.path.join(data_dir, "processed-config.yaml")
    if os.path.exists(cfg_path):
        import yaml
        with open(cfg_path) as f:
            cfg = yaml.safe_load(f) or {}
        for name, h in sorted((cfg.get("hosts") or {}).items()):
            if (h or {}).get("pcap_enabled"):
                pcap_hosts.append(name)
        if (cfg.get("experimental") or {}).get("host_cpu_threshold"):
            cpu_hosts = sorted((cfg.get("hosts") or {}).keys())
    hosts_of = {"object-path:pcap": pcap_hosts,
                "object-path:cpu-model": cpu_hosts,
                "object-path:other": other_hosts}

    device = elig.get("device-span", 0)
    print(f"device-span coverage: {device}/{rounds} rounds; top "
          f"blockers and remediation:", file=out)
    shown = 0
    for name, n in sorted(elig.items(), key=lambda kv: -kv[1]):
        if name == "device-span":
            continue
        hint = _EXPLAIN.get(name)
        hosts = ", ".join(hosts_of.get(name, [])[:8]) or "(see config)"
        text = (hint[0].format(hosts=hosts) if hint
                else "no registered remediation for this reason.")
        pct = 100.0 * n / rounds if rounds else 0.0
        print(f"  {name} — {n} rounds ({pct:.1f}%)", file=out)
        print(f"      {text}", file=out)
        shown += 1
        if shown >= 6:
            break
    if not shown:
        print("  (every round ran on the device — nothing to "
              "remediate)", file=out)
    return True


def run_config(config_path: str, data_dir: str | None = None) -> str:
    """Run a YAML config with the flight recorder forced on; returns
    the data directory."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation

    config = ConfigOptions.from_file(config_path)
    config.experimental.flight_recorder = "on"
    if data_dir is not None:
        config.general.data_directory = data_dir
    _manager, summary = run_simulation(config, write_data=True)
    if not summary.ok:
        for err in summary.plugin_errors:
            print(f"[trace] plugin error: {err}", file=sys.stderr)
    return config.general.data_directory


def smoke(n_hosts: int) -> int:
    """50-host traced tgen TCP tier: summary + eligibility must
    render and account for every round, the drop-cause counters must
    conserve, and the Chrome export must carry a non-empty
    per-connection counter track (the ./setup trace target)."""
    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation
    from shadow_tpu.tools.netgen import tcp_stream_yaml

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "trace-smoke")
        # Default nbytes keeps every client mid-stream at stop_time
        # (the generator's expected_final_state is `running`).
        text = tcp_stream_yaml(n_hosts, loss=0.005, stop_time="2s",
                               seed=11, scheduler="tpu")
        config = ConfigOptions.from_yaml_text(text)
        config.experimental.flight_recorder = "on"
        config.experimental.sim_netstat = "on"
        config.general.data_directory = base
        _manager, summary = run_simulation(config, write_data=True)
        if not summary.ok:
            print(f"trace smoke: sim failed: {summary.plugin_errors}",
                  file=sys.stderr)
            return 1
        chrome_out = os.path.join(base, "chrome-trace.json")
        ok = summarize(base, chrome_out=chrome_out)
        if not ok:
            print("trace smoke: eligibility report did not account "
                  "for all rounds", file=sys.stderr)
            return 1
        if not net_report(base):
            print("trace smoke: drop-cause counters do not conserve",
                  file=sys.stderr)
            return 1
        explain_report(base)
        with open(chrome_out) as f:
            doc = json.load(f)
        slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        if not slices:
            print("trace smoke: chrome export has no slices",
                  file=sys.stderr)
            return 1
        counters = [e for e in doc["traceEvents"]
                    if e.get("ph") == "C"]
        if not counters:
            print("trace smoke: chrome export has no sim-netstat "
                  "counter track", file=sys.stderr)
            return 1
    print(f"trace smoke: ok ({n_hosts} hosts, {summary.rounds} rounds "
          f"fully attributed, drops conserved, "
          f"{len(counters)} counter events)")
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] in ("net", "explain"):
        # Subcommands: `trace net DATA_DIR [--top N]`,
        #              `trace explain DATA_DIR`.
        sub = argparse.ArgumentParser(
            prog=f"shadow_tpu.tools.trace {argv[0]}")
        sub.add_argument("data_dir")
        if argv[0] == "net":
            sub.add_argument("--top", type=int, default=10,
                             help="connections in the report "
                                  "(default 10)")
        sargs = sub.parse_args(argv[1:])
        from shadow_tpu.utils.platform import honor_platform_env
        honor_platform_env()
        if argv[0] == "net":
            return 0 if net_report(sargs.data_dir,
                                   top_n=sargs.top) else 1
        return 0 if explain_report(sargs.data_dir) else 1

    ap = argparse.ArgumentParser(prog="shadow_tpu.tools.trace",
                                 description=__doc__)
    ap.add_argument("data_dir", nargs="?",
                    help="data directory of a flight-recorded run")
    ap.add_argument("--run", metavar="CONFIG",
                    help="run this YAML config with the flight "
                         "recorder on, then summarize")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the 50-host traced smoke sim and exit "
                         "nonzero unless the report renders")
    ap.add_argument("--hosts", type=int, default=50,
                    help="host count for --smoke (default 50)")
    args = ap.parse_args(argv)

    from shadow_tpu.utils.platform import honor_platform_env
    honor_platform_env()

    if args.smoke:
        return smoke(args.hosts)
    if args.run is not None:
        data_dir = run_config(args.run, args.data_dir)
    elif args.data_dir is not None:
        data_dir = args.data_dir
    else:
        ap.print_usage(sys.stderr)
        print("trace: a data directory, --run, or --smoke is required",
              file=sys.stderr)
        return 2
    ok = summarize(data_dir, chrome_out=args.chrome)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
