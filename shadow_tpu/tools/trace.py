"""Flight-recorder & sim-netstat CLI: summarize, attribute, export.

    python -m shadow_tpu.tools.trace DATA_DIR            # summarize
    python -m shadow_tpu.tools.trace DATA_DIR --chrome out.json
    python -m shadow_tpu.tools.trace net DATA_DIR        # TCP report
    python -m shadow_tpu.tools.trace fabric DATA_DIR     # queue report
    python -m shadow_tpu.tools.trace fct DATA_DIR        # FCT table
    python -m shadow_tpu.tools.trace kern DATA_DIR       # stage report
    python -m shadow_tpu.tools.trace explain DATA_DIR    # remediation
    python -m shadow_tpu.tools.trace --run sim.yaml      # run + summarize
    python -m shadow_tpu.tools.trace --smoke [--hosts N] # CI smoke

`kern` prints the device-kernel observatory report
(docs/OBSERVABILITY.md "Device-kernel observatory"): per span family,
the per-stage table — fires, active-lane sums, occupancy and the
estimated share of the measured device us/host/round — plus the
fires-vs-micro_iters conservation verdict and a crossover-attribution
verdict naming the stages that dominate the fitted device slope.  The
whole report reproduces from the artifact (`kernel-sim.bin`) plus
sim-stats.json alone.

`fabric` prints the fabric-observatory report: per-link utilization,
the queue-depth table (top links by peak sampled CoDel depth, with
sojourn/drop/stall series) and the byte-conservation verdict
(per-interface bytes enqueued == delivered + dropped + queued, drops
reconciled against the TEL_* causes).  `fct` prints the
flow-completion-time percentile table per flow class (service port).

`net` prints the sim-netstat report: the drop-attribution table with
its conservation check (per-cause counters must sum to the sim's
packets_dropped) and a top-N per-connection table (retransmits, final
srtt/cwnd, buffer peaks) from telemetry-sim.bin.  `explain` maps the
eligibility audit's top blockers to concrete remediation hints (which
hosts force the object path and why, which knobs re-enable spans).

Reads the artifacts a flight-recorded run leaves in its data
directory (`sim-stats.json`, `flight-sim.bin`, `flight-wall.json` —
docs/OBSERVABILITY.md) and prints:

- the sim-time channel summary (records, spans by family, aborts),
- the device-eligibility attribution report (one reason code per
  conservative round; the counts always sum to the round total),
- the wall-time phase breakdown (export/convert/compile/execute/
  import/barrier/host-loop),

and exports Chrome trace-event JSON (--chrome) that loads in Perfetto
with rounds, spans, and phases as nested slices.

`--run` executes a config with the flight recorder forced on and then
summarizes its data directory.  `--smoke` builds a small tgen TCP
tier (tools/netgen), runs it traced, and exits non-zero unless the
summary renders and the eligibility report accounts for 100% of
rounds — the `./setup trace` target.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load(data_dir: str):
    stats_path = os.path.join(data_dir, "sim-stats.json")
    if not os.path.exists(stats_path):
        raise FileNotFoundError(
            f"{stats_path} not found — not a simulation data dir?")
    with open(stats_path) as f:
        stats = json.load(f)
    sim_bytes = b""
    sim_path = os.path.join(data_dir, "flight-sim.bin")
    if os.path.exists(sim_path):
        with open(sim_path, "rb") as f:
            sim_bytes = f.read()
    wall = None
    wall_path = os.path.join(data_dir, "flight-wall.json")
    if os.path.exists(wall_path):
        with open(wall_path) as f:
            wall = json.load(f)
    tel_bytes = b""
    tel_path = os.path.join(data_dir, "telemetry-sim.bin")
    if os.path.exists(tel_path):
        with open(tel_path, "rb") as f:
            tel_bytes = f.read()
    sc_bytes = b""
    sc_path = os.path.join(data_dir, "syscalls-sim.bin")
    if os.path.exists(sc_path):
        with open(sc_path, "rb") as f:
            sc_bytes = f.read()
    fab_bytes = b""
    fab_path = os.path.join(data_dir, "fabric-sim.bin")
    if os.path.exists(fab_path):
        with open(fab_path, "rb") as f:
            fab_bytes = f.read()
    return stats, sim_bytes, wall, tel_bytes, sc_bytes, fab_bytes


def summarize(data_dir: str, chrome_out: str | None = None,
              out=None) -> bool:
    """Print the trace summary + eligibility report; write the Chrome
    export when asked.  Returns True when the eligibility counts
    account for 100% of rounds."""
    if out is None:
        out = sys.stdout  # resolved at call time (pytest capsys swaps it)
    from shadow_tpu.trace.audit import render_report
    from shadow_tpu.trace.events import (FLIGHT_REC_BYTES, FR_ROUND,
                                         FR_SPAN_ABORT, FR_SPAN_COMMIT,
                                         FR_SPAN_START, iter_records)

    stats, sim_bytes, wall, tel_bytes, sc_bytes, fab_bytes = \
        _load(data_dir)
    rounds = stats.get("rounds", 0)
    metrics = stats.get("metrics", {})
    elig = metrics.get("wall", {}).get("eligibility", {})

    print(f"trace summary for {data_dir}", file=out)
    print(f"  rounds {rounds}, packets {stats.get('packets_sent', 0)}, "
          f"events {stats.get('events', 0)}, sim end "
          f"{stats.get('end_time_ns', 0) / 1e9:.3f}s", file=out)

    if sim_bytes:
        kinds = {FR_ROUND: 0, FR_SPAN_START: 0, FR_SPAN_COMMIT: 0,
                 FR_SPAN_ABORT: 0}
        span_rounds = 0
        for _t, kind, _a, _b, c in iter_records(sim_bytes):
            kinds[kind] = kinds.get(kind, 0) + 1
            if kind == FR_SPAN_COMMIT:
                span_rounds += c
        n_recs = len(sim_bytes) // FLIGHT_REC_BYTES
        from shadow_tpu.trace.events import (FR_FAULT_KILL,
                                             FR_FAULT_QUARANTINE)
        n_faults = sum(n for k, n in kinds.items()
                       if FR_FAULT_KILL <= k <= FR_FAULT_QUARANTINE)
        fault_s = f", {n_faults} fault injections" if n_faults else ""
        print(f"  sim-time channel: {n_recs} records "
              f"({kinds[FR_ROUND]} round, {kinds[FR_SPAN_COMMIT]} span "
              f"commits covering {span_rounds} rounds, "
              f"{kinds[FR_SPAN_ABORT]} aborts{fault_s})", file=out)
    else:
        print("  sim-time channel: absent (run with "
              "experimental.flight_recorder: on)", file=out)

    ok = bool(elig) and sum(elig.values()) == rounds
    if elig:
        print(render_report(elig, rounds), file=out)
    else:
        print("  (no eligibility block in sim-stats.json — pre-trace "
              "artifact?)", file=out)

    phases = metrics.get("wall", {}).get("phases")
    if phases:
        print("wall-time phases:", file=out)
        for name, ns in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(f"  {name:<16} {ns / 1e9:10.3f}s", file=out)

    ks_bytes = _kern_bytes(data_dir)
    if ks_bytes:
        from shadow_tpu.trace.events import KS_REC_BYTES
        print(f"  device-kernel observatory: "
              f"{len(ks_bytes) // KS_REC_BYTES} committed-span "
              f"records (`trace kern` for the per-stage table)",
              file=out)

    if chrome_out is not None:
        from shadow_tpu.trace.chrome import chrome_trace
        from shadow_tpu.trace.events import split_fabric
        fb = b""
        if fab_bytes:
            fb, _fct = split_fabric(fab_bytes)
        top_n = _chrome_top_n(data_dir)
        doc = chrome_trace(sim_bytes, wall, tel_bytes, sc_bytes, fb,
                           top_n, ks_bytes=ks_bytes)
        with open(chrome_out, "w") as f:
            json.dump(doc, f)
        print(f"chrome trace: {chrome_out} "
              f"({len(doc['traceEvents'])} events — load in Perfetto "
              f"or chrome://tracing)", file=out)
    return ok


def drop_report(stats: dict, out=None) -> bool:
    """The drop-attribution table + conservation check.  Returns True
    when every wire drop is attributed and the causes sum exactly to
    packets_dropped."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.trace.events import TEL_NAMES, TEL_WIRE_N

    drops = stats.get("metrics", {}).get("sim", {}).get(
        "netstat", {}).get("drops", {})
    total = stats.get("packets_dropped", 0)
    wire = set(TEL_NAMES[:TEL_WIRE_N])
    print("packet-drop attribution (one cause per drop):", file=out)
    wire_sum = 0
    width = max([len(k) for k in drops] + [16])
    for name, n in sorted(drops.items(), key=lambda kv: -kv[1]):
        kind = "wire" if name in wire else (
            "tcp-discard" if name != "unattributed" else "GAP")
        print(f"  {name:<{width}}  {n:>10}  [{kind}]", file=out)
        if name in wire:
            wire_sum += n
    ok = wire_sum == total and "unattributed" not in drops
    if ok:
        print(f"  {'total (wire)':<{width}}  {wire_sum:>10}  "
              f"== packets_dropped ({total}): conserved", file=out)
    else:
        print(f"  total (wire) {wire_sum} != packets_dropped {total} "
              f"— ATTRIBUTION GAP", file=out)
    return ok


def net_report(data_dir: str, top_n: int = 10, out=None) -> bool:
    """`trace net`: drop attribution + the top-N connection table
    from telemetry-sim.bin.  Returns the conservation verdict."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.net.graph import format_ip
    from shadow_tpu.trace.events import TEL_REC_BYTES
    from shadow_tpu.trace.netstat import (group_by_conn,
                                          top_by_retransmits)

    stats, _sim, _wall, tel_bytes, _sc, _fab = _load(data_dir)
    ok = drop_report(stats, out=out)

    if not tel_bytes:
        print("sim-netstat channel: absent (run with "
              "experimental.sim_netstat: on)", file=out)
        return ok
    by_conn = group_by_conn(tel_bytes)
    n_recs = len(tel_bytes) // TEL_REC_BYTES
    print(f"sim-netstat: {n_recs} samples over {len(by_conn)} "
          f"connections", file=out)
    ranked = top_by_retransmits(by_conn, top_n)
    print(f"top {len(ranked)} connections by retransmits:", file=out)
    print(f"  {'connection':<32} {'rtx':>6} {'sack':>5} "
          f"{'marks':>6} {'srtt ms':>8} {'cwnd kB':>8} {'sndbuf':>8} "
          f"{'rcvbuf':>8}", file=out)
    for key in ranked:
        host, lport, rport, rip = key
        recs = by_conn[key]
        last = recs[-1]
        name = f"h{host}:{lport}->{format_ip(rip)}:{rport}"
        print(f"  {name:<32} {last[13]:>6} {last[14]:>5} "
              f"{last[15]:>6} "
              f"{last[8] / 1e6:>8.2f} {last[6] / 1024:>8.1f} "
              f"{max(r[11] for r in recs):>8} "
              f"{max(r[12] for r in recs):>8}", file=out)
    return ok


def _chrome_top_n(data_dir: str) -> int:
    """The experimental.chrome_top_n knob from the processed config
    (shared by every per-entity counter-track family)."""
    from shadow_tpu.trace.chrome import DEFAULT_TOP_N
    exp = _processed_config(data_dir).get("experimental") or {}
    try:
        return max(int(exp.get("chrome_top_n", DEFAULT_TOP_N)), 1)
    except (TypeError, ValueError):
        return DEFAULT_TOP_N


def fabric_report(data_dir: str, top_n: int = 10, out=None) -> bool:
    """`trace fabric`: per-link utilization + queue-depth table +
    the byte-conservation verdict.  Returns False on a conservation
    violation (the gate's exit code)."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.trace.events import iter_fb_records, split_fabric
    from shadow_tpu.trace.fabricstat import (group_by_host,
                                             top_by_peak_depth)

    stats, _sim, _wall, _tel, _sc, fab_bytes = _load(data_dir)
    fab = stats.get("metrics", {}).get("sim", {}).get("fabric", {})
    viol = fab.get("violations")
    print("fabric observatory (per-interface byte conservation):",
          file=out)
    for key in ("enqueued_pkts", "delivered_pkts", "dropped_pkts",
                "queued_pkts", "enqueued_bytes", "delivered_bytes",
                "dropped_bytes", "queued_bytes", "peak_queue_depth",
                "refill_stalls", "marked_pkts"):
        if key in fab:
            print(f"  {key:<18} {fab[key]:>14}", file=out)
    marks = fab.get("marks") or {}
    for cause, n in sorted(marks.items()):
        print(f"    mark:{cause:<12} {n:>14}", file=out)
    ok = viol == 0
    if viol is None:
        print("  (no fabric block in sim-stats.json — pre-fabric "
              "artifact?)", file=out)
        ok = False
    elif ok:
        print("  conservation: enqueued == delivered + dropped + "
              "queued on every interface, drops reconciled against "
              "the TEL_* causes", file=out)
    else:
        print(f"  conservation: {viol} interface(s) VIOLATED — bytes "
              f"lost outside the attributed drop causes", file=out)

    if not fab_bytes:
        print("fabric channel: absent (run with "
              "experimental.sim_fabricstat: on)", file=out)
        return ok
    fb, _fct = split_fabric(fab_bytes)
    by_host = group_by_host(fb)
    n_recs = sum(len(v) for v in by_host.values())
    print(f"fabric channel: {n_recs} samples over {len(by_host)} "
          f"links", file=out)
    # sim duration for the utilization column (end of the last sample)
    end_ns = max((r[0] for r in iter_fb_records(fb)), default=0)
    ranked = top_by_peak_depth(by_host, top_n)
    print(f"top {len(ranked)} links by peak queue depth:", file=out)
    print(f"  {'link':<8} {'peak q':>7} {'max soj ms':>11} "
          f"{'drops':>7} {'marks':>7} {'stalls':>7} {'util %':>7}",
          file=out)
    cfg = _processed_config(data_dir)
    names = _host_names(cfg)
    bw_up = _host_bw_table(cfg, names)
    for host in ranked:
        recs = by_host[host]
        last = recs[-1]
        peak = max(r[3] for r in recs)
        soj = max(r[5] for r in recs) / 1e6
        stalls = last[10] + last[12]
        bw = bw_up[host] if 0 <= host < len(bw_up) else 0
        util = (f"{100.0 * last[14] * 8 / (bw * end_ns / 1e9):7.1f}"
                if end_ns and bw else f"{'-':>7}")
        label = names[host] if 0 <= host < len(names) else f"h{host}"
        print(f"  {label:<8.8} {peak:>7} {soj:>11.2f} "
              f"{last[7]:>7} {last[8]:>7} {stalls:>7} {util}",
              file=out)
    return ok


def _host_bw_table(cfg: dict, names: list) -> list:
    """Host-id -> uplink bits/s from the processed config: the
    per-host override when present, else the graph node's
    host_bandwidth_up (the common case — every canonical generator
    sets bandwidth in the GML).  One GML parse for the whole table;
    0 when unresolvable (the utilization column then reads '-')."""
    node_bw: dict = {}
    gspec = (cfg.get("network") or {}).get("graph") or {}
    inline = gspec.get("inline")
    if gspec.get("type") == "gml" and inline:
        try:
            from shadow_tpu.net.graph import NetworkGraph
            g = NetworkGraph.from_gml(inline)
            node_bw = {gml_id: node.bandwidth_up_bits or 0
                       for gml_id, node in g.by_gml_id.items()}
        except Exception:  # noqa: BLE001 — report-only fallback
            node_bw = {}
    out = []
    hosts = cfg.get("hosts") or {}
    for name in names:
        h = hosts.get(name) or {}
        out.append(int(h.get("bandwidth_up")
                       or node_bw.get(h.get("network_node_id"), 0)))
    return out


def fct_report(data_dir: str, out=None) -> bool:
    """`trace fct`: the flow-completion-time percentile table per
    flow class (service port).  Returns True when flow records
    exist."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.trace.events import iter_fct_records, split_fabric
    from shadow_tpu.trace.fabricstat import fct_table

    _stats, _sim, _wall, _tel, _sc, fab_bytes = _load(data_dir)
    if not fab_bytes:
        print("fabric channel: absent (run with "
              "experimental.sim_fabricstat: on)", file=out)
        return False
    _fb, fct_bytes = split_fabric(fab_bytes)
    rows = list(iter_fct_records(fct_bytes))
    table = fct_table(rows)
    if not table:
        print("no flow records (no TCP payload moved)", file=out)
        return False
    print(f"flow completion times ({len(rows)} endpoint records):",
          file=out)
    print(f"  {'class':>6} {'flows':>6} {'done':>5} {'MB':>9} "
          f"{'marks':>7} {'mk/1k':>6} "
          f"{'p50 ms':>9} {'p99 ms':>9} {'p999 ms':>9}", file=out)
    for cls, ent in table.items():
        print(f"  {cls:>6} {ent['flows']:>6} {ent['complete']:>5} "
              f"{ent['bytes'] / 1e6:>9.2f} "
              f"{ent['marks']:>7} {ent['mark_permille']:>6} "
              f"{ent['p50_ns'] / 1e6:>9.2f} "
              f"{ent['p99_ns'] / 1e6:>9.2f} "
              f"{ent['p999_ns'] / 1e6:>9.2f}", file=out)
    return True


def _kern_bytes(data_dir: str) -> bytes:
    """kernel-sim.bin's content (b"" when the observatory was off)."""
    path = os.path.join(data_dir, "kernel-sim.bin")
    if not os.path.exists(path):
        return b""
    with open(path, "rb") as f:
        return f.read()


def kern_report(data_dir: str, out=None) -> bool:
    """`trace kern`: the device-kernel observatory report — per-stage
    fires/lanes/occupancy table with the attributed share of each
    family's measured device slope, the fires-vs-micro_iters
    conservation verdict, and a crossover-attribution verdict.
    Everything derives from kernel-sim.bin + sim-stats.json alone.
    Returns the conservation verdict (the gate's exit code)."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.trace.kernstat import (attribution,
                                           check_conservation,
                                           family_label, family_totals,
                                           family_warm_wall_s,
                                           low_occupancy_stages,
                                           render_table)

    stats, _sim, _wall, _tel, _sc, _fab = _load(data_dir)
    ks_bytes = _kern_bytes(data_dir)
    if not ks_bytes:
        print("device-kernel observatory: no records (run with "
              "experimental.kernel_observatory: on and a device-"
              "routed workload — e.g. tpu_device_spans: force)",
              file=out)
        # Vacuously conserved: zero committed spans, zero records.
        return True
    dispatch = stats.get("metrics", {}).get("wall", {}).get(
        "dispatch", {})
    render_table(ks_bytes, dispatch, out=out)
    dropped = stats.get("metrics", {}).get("sim", {}).get(
        "kern", {}).get("dropped", 0)
    ok, problems = check_conservation(ks_bytes, dispatch, dropped)
    if ok:
        print("conservation: committed trips reconcile exactly "
              "against dispatch micro_iters", file=out)
    else:
        print("conservation: VIOLATED", file=out)
        for p in problems[:8]:
            print(f"  {p}", file=out)
    # Crossover-attribution verdict: which stages own the device
    # slope the crossover ladder fits (ROADMAP item 3's per-stage
    # before/after).
    for family, ent in sorted(family_totals(ks_bytes).items()):
        wall_s = family_warm_wall_s(dispatch, family)
        att = attribution(ent, wall_s)
        ranked = sorted(att.items(),
                        key=lambda kv: -kv[1]["share_permille"])[:3]
        if not ranked:
            continue
        hr = ent["hosts"] * ent["rounds"]
        slope = wall_s * 1e6 / hr if hr else 0.0
        tops = ", ".join(
            f"{sname} ({row['share_permille'] / 10:.0f}% ~ "
            f"{row['us_per_host_round']:.2f} us)"
            for sname, row in ranked)
        print(f"crossover attribution [{family_label(family)}]: "
              f"warm slope {slope:.2f} us/host/round; dominated by "
              f"{tops}", file=out)
        low = [sname for sname, _occ in low_occupancy_stages(ent)]
        if low:
            print(f"  low-occupancy stages (<5% of lane slots): "
                  f"{', '.join(low)} — vector width mostly burns "
                  f"masked-out lanes there", file=out)
    # Overlapped-pipeline report (ISSUE 16): per-family device-idle /
    # host-idle fractions over the async dispatch window — the
    # measured answer to "did the double buffer actually hide the
    # host work".
    for key in sorted(dispatch):
        if not key.startswith("device_span_"):
            continue
        ov = (dispatch.get(key) or {}).get("overlap") or {}
        if not ov.get("windows"):
            continue
        fam = key[len("device_span_"):]
        print(f"overlap [{fam}]: {ov['windows']} speculative "
              f"window(s) dispatched, {ov.get('hits', 0)} landed, "
              f"{ov.get('refusals', 0)} refused "
              f"({ov.get('stale_refusals', 0)} stale); device idle "
              f"{100.0 * float(ov.get('device_idle_frac', 0.0)):.0f}%,"
              f" host idle "
              f"{100.0 * float(ov.get('host_idle_frac', 0.0)):.0f}% "
              f"of the {ov.get('pipe_wall_s', 0.0):.3f}s pipelined "
              f"wall", file=out)
    return ok


def _processed_config(data_dir: str) -> dict:
    """The processed-config.yaml next to sim-stats.json ({} when
    absent) — the ONE parse every report shares."""
    cfg_path = os.path.join(data_dir, "processed-config.yaml")
    if not os.path.exists(cfg_path):
        return {}
    import yaml
    with open(cfg_path) as f:
        return yaml.safe_load(f) or {}


def _host_names(cfg: dict) -> list:
    """Host-id -> name mapping: host ids follow sorted-name order
    (core/manager.py builds hosts that way), so the processed config's
    sorted host keys ARE the id order."""
    return sorted((cfg.get("hosts") or {}).keys())


def _strace_line_counts(data_dir: str, names: list) -> dict:
    """(host_id, pid) -> strace line count, from the per-process
    .strace files (named <proc>.<pid>.strace in each host dir)."""
    out: dict = {}
    for host_id, name in enumerate(names):
        hdir = os.path.join(data_dir, "hosts", name)
        if not os.path.isdir(hdir):
            continue
        for fn in os.listdir(hdir):
            if not fn.endswith(".strace"):
                continue
            try:
                pid = int(fn[:-len(".strace")].rsplit(".", 1)[1])
            except (IndexError, ValueError):
                continue
            with open(os.path.join(hdir, fn), "rb") as f:
                out[(host_id, pid)] = f.read().count(b"\n")
    return out


def sys_report(data_dir: str, top_n: int = 10, out=None) -> bool:
    """`trace sys`: the syscall-observatory report — disposition table
    with conservation, top syscalls by count and wall, and the IPC
    round-trip wall breakdown.  Returns False on a conservation gap
    (a record with an out-of-range disposition, or a managed process
    whose dispatch-record count disagrees with its strace line count)."""
    if out is None:
        out = sys.stdout
    from shadow_tpu.host.syscalls_native import syscall_name
    from shadow_tpu.trace.events import SC_N, SC_SHIM, iter_sc_records

    stats, _sim, _wall, _tel, sc_bytes, _fab = _load(data_dir)
    metrics = stats.get("metrics", {})
    disp = metrics.get("sim", {}).get("syscalls", {}).get(
        "dispositions", {})

    print("syscall observatory (one SC_* disposition per dispatch):",
          file=out)
    if disp:
        width = max(len(k) for k in disp)
        for name, n in sorted(disp.items(), key=lambda kv: -kv[1]):
            print(f"  {name:<{width}}  {n:>10}", file=out)
    else:
        print("  (no Python-dispatched syscalls — engine-resident "
              "apps sit outside this accounting)", file=out)

    ok = True
    if not sc_bytes:
        print("syscall channel: absent (run with "
              "experimental.syscall_observatory: on)", file=out)
    else:
        # Per-record accounting: counts by syscall number + per-process
        # dispatch counts for the strace cross-check.
        by_sysno: dict = {}
        by_proc: dict = {}
        shim_total = 0
        bad_disp = 0
        n_recs = 0
        for rec in iter_sc_records(sc_bytes):
            n_recs += 1
            _t0, _t1, host, pid, _tid, sysno, _rc, d, aux = rec
            if not 0 <= d < SC_N:
                bad_disp += 1
            if d == SC_SHIM:
                shim_total += aux
            if sysno >= 0:
                by_sysno[sysno] = by_sysno.get(sysno, 0) + 1
                by_proc[(host, pid)] = by_proc.get((host, pid), 0) + 1
        print(f"syscall channel: {n_recs} records "
              f"({sum(by_sysno.values())} dispatches, {shim_total} "
              f"shim-handled time reads)", file=out)
        if bad_disp:
            ok = False
            print(f"  {bad_disp} record(s) with out-of-range "
                  f"disposition — CONSERVATION GAP", file=out)

        # Wall per family (metrics.wall.ipc) joined onto the counts.
        fams = metrics.get("wall", {}).get("ipc", {}).get("families",
                                                          {})
        ranked = sorted(by_sysno.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:top_n]
        print(f"top {len(ranked)} syscalls by count:", file=out)
        print(f"  {'syscall':<18} {'count':>8} {'wall ms':>9} "
              f"{'p50 us':>8} {'p99 us':>8}", file=out)
        for sysno, cnt in ranked:
            name = syscall_name(sysno)
            f = fams.get(name, {})
            print(f"  {name:<18} {cnt:>8} "
                  f"{f.get('total_ns', 0) / 1e6:>9.2f} "
                  f"{f.get('p50_ns', 0) / 1e3:>8.1f} "
                  f"{f.get('p99_ns', 0) / 1e3:>8.1f}", file=out)
        if fams:
            by_wall = sorted(fams.items(),
                             key=lambda kv: -kv[1]["total_ns"])[:top_n]
            print(f"top {len(by_wall)} syscalls by wall:", file=out)
            for name, f in by_wall:
                print(f"  {name:<18} {f['count']:>8} "
                      f"{f['total_ns'] / 1e6:>9.2f} "
                      f"{f['p50_ns'] / 1e3:>8.1f} "
                      f"{f['p99_ns'] / 1e3:>8.1f}", file=out)

        # Strace cross-check: one strace line per dispatch, so each
        # managed process's dispatch-record count must equal its
        # .strace line count (when strace logging was on).  A capped
        # channel (metrics.sim.syscalls.dropped > 0) legitimately
        # undercounts — report the truncation instead of a false gap.
        chan_dropped = metrics.get("sim", {}).get("syscalls", {}).get(
            "dropped", 0)
        if chan_dropped:
            print(f"strace cross-check: skipped — channel truncated "
                  f"({chan_dropped} records dropped at the per-host "
                  f"cap)", file=out)
        else:
            straces = _strace_line_counts(
                data_dir, _host_names(_processed_config(data_dir)))
            checked = mismatched = 0
            for key, n in sorted(by_proc.items()):
                want = straces.get(key)
                if want is None:
                    continue
                checked += 1
                if n != want:
                    mismatched += 1
                    ok = False
                    print(f"  h{key[0]} pid{key[1]}: {n} dispatch "
                          f"records != {want} strace lines — "
                          f"CONSERVATION GAP", file=out)
            if checked:
                print(f"strace cross-check: {checked} process(es), "
                      f"{'all consistent' if not mismatched else f'{mismatched} mismatched'}",
                      file=out)

    ipc = metrics.get("wall", {}).get("ipc", {})
    if ipc:
        mc = ipc.get("memcopy", {})
        print(f"ipc round trips: {ipc.get('round_trips', 0)} | wall "
              f"wait {ipc.get('wait_ns', 0) / 1e9:.3f}s, dispatch "
              f"{ipc.get('dispatch_ns', 0) / 1e9:.3f}s, resume "
              f"{ipc.get('resume_ns', 0) / 1e9:.3f}s, memcopy "
              f"{(mc.get('read_ns', 0) + mc.get('write_ns', 0)) / 1e9:.3f}s "
              f"({mc.get('calls', 0)} copies)", file=out)
    return ok


# Eligibility-blocker remediation hints (`trace explain`), keyed by
# the EL_NAMES the audit reports.  {hosts} interpolates the offending
# host list where the processed config identifies one.
_EXPLAIN = {
    "object-path:pcap": (
        "pcap capture pins these hosts to the Python object path: "
        "{hosts}.  Disable pcap_enabled on them (or accept per-round "
        "spans capped at experimental.pcap_span_cap).",),
    "object-path:cpu-model": (
        "the host CPU model (experimental.host_cpu_threshold) forces "
        "the object path: {hosts}.  Unset it to let these hosts join "
        "engine/device spans.",),
    "object-path:py-task": (
        "engine hosts briefly carried Python-side work (process "
        "spawn/shutdown tasks); normal at sim start and end.",),
    "object-path:other": (
        "a host config (e.g. strace_logging_mode) keeps these hosts "
        "off the native plane: {hosts}.",),
    "engine-span:device-off": (
        "device spans are disabled (experimental.tpu_device_spans: "
        "off); set it to auto or force.",),
    "engine-span:ineligible-family": (
        "no device-span family fits this sim's shape — the PHOLD "
        "family needs pure udp-mesh/phold apps, the TCP family needs "
        "the tgen steady-stream tier (netgen.tcp_stream_yaml).",),
    "engine-span:transient": (
        "the sim was transiently outside the TCP family's modelled "
        "domain (handshake/close stretches); steady-state rounds "
        "still reach the device.",),
    "engine-span:abort-rollback": (
        "device spans aborted (capacity or domain); see dispatch."
        "device_span_*.aborts and grow the runner caps if persistent.",),
    "engine-span:cold-budget": (
        "the device compile budget was not yet earned (1% of wall); "
        "longer runs probe and route automatically.",),
    "engine-span:routed": (
        "the router measured the C++ span faster than the device at "
        "this scale — expected on small sims or CPU backends.",),
    "engine-span:py-limit": (
        "spans were capped before windows could touch an object-path "
        "host; reduce object-path hosts to lengthen spans.",),
    "per-round:forced-device": (
        "forced-device audit mode (tpu_min_device_batch <= 0) runs "
        "every round through the jitted kernel by design.",),
    "per-round:scheduler": (
        "this scheduler has no span path; use scheduler: tpu for "
        "engine/device spans.",),
    "per-round:outbox": (
        "object-path packets were pending in the propagator outbox at "
        "the round boundary; the fabric observatory names the hottest "
        "queue below when its channel was on.",),
    "per-round:callback-host": (
        "a host can fire Python callbacks mid-event (Python-owned "
        "sockets), which excludes the whole sim from C++ spans.",),
    "engine-span:managed-quiescent": (
        "the syscall service plane's quiescence gate served these "
        "rounds inside engine spans while every managed process sat "
        "parked — this is span COVERAGE, not a blocker.",),
}


def _managed_blockers(data_dir: str, sc_bytes: bytes, out,
                      elig: dict | None = None,
                      rounds: int = 0) -> None:
    """Join the eligibility audit with the syscall channel: when
    managed processes keep rounds off the span path (their hosts carry
    Python-side work every round they run), print the quiescence
    fraction (rounds the service plane's gate DID route into spans),
    the top blocking syscalls preventing further span coverage, and
    each host's last blocking syscall."""
    from shadow_tpu.host.syscalls_native import syscall_name
    from shadow_tpu.trace.events import SC_PARKED, iter_sc_records

    # One parse of the processed config yields both the id->name order
    # and the managed-host set.
    cfg = _processed_config(data_dir)
    names = _host_names(cfg)
    managed_hosts = set()
    for name in names:
        h = (cfg.get("hosts") or {}).get(name) or {}
        for p in h.get("processes", []) or []:
            # Managed processes are configured by filesystem path
            # (core/manager._schedule_spawn's dispatch rule).
            if "/" in str(p.get("path", "")):
                managed_hosts.add(name)
    if not managed_hosts:
        return
    if elig and rounds:
        # Quiescence fraction: rounds the service plane's gate turned
        # into engine-span coverage while every managed process sat
        # parked (the EL_SVC_QUIESCENT attribution).
        q = elig.get("engine-span:managed-quiescent", 0)
        print(f"  managed quiescence: {q}/{rounds} rounds "
              f"({100.0 * q / rounds:.1f}%) served inside engine "
              f"spans while the managed fleet was parked", file=out)
    if not sc_bytes:
        print(f"  managed hosts present ({len(managed_hosts)}): run "
              f"with experimental.syscall_observatory: on to see each "
              f"host's last blocking syscall here.", file=out)
        return
    last_park: dict = {}  # host_id -> (t, pid, tid, sysno)
    park_by_sysno: dict = {}  # sysno -> park count
    for rec in iter_sc_records(sc_bytes):
        t0, _t1, host, pid, tid, sysno, _rc, disp, _aux = rec
        if disp == SC_PARKED and sysno >= 0:
            last_park[host] = (t0, pid, tid, sysno)
            park_by_sysno[sysno] = park_by_sysno.get(sysno, 0) + 1
    if park_by_sysno:
        top = sorted(park_by_sysno.items(), key=lambda kv: -kv[1])[:5]
        print("  top blocking syscalls preventing span coverage: "
              + ", ".join(f"{syscall_name(n)} ({c} parks)"
                          for n, c in top), file=out)
    print(f"  managed hosts holding rounds on the Python path "
          f"({len(managed_hosts)}):", file=out)
    shown = 0
    for name in sorted(managed_hosts):
        host_id = names.index(name) if name in names else -1
        park = last_park.get(host_id)
        if park is None:
            print(f"    {name}: no blocking syscall recorded", file=out)
        else:
            t, pid, tid, sysno = park
            print(f"    {name}: pid {pid} tid {tid} last blocked in "
                  f"{syscall_name(sysno)} at {t / 1e9:.3f}s", file=out)
        shown += 1
        if shown >= 8:
            break


def _hottest_queue(data_dir: str, fab_bytes: bytes, out) -> None:
    """Join the eligibility audit with the fabric channel: when rounds
    stall on outbox pressure, name the link whose router queue peaked
    hottest (depth and head sojourn) — the congestion point to debug
    first."""
    from shadow_tpu.trace.events import split_fabric
    from shadow_tpu.trace.fabricstat import (group_by_host,
                                             top_by_peak_depth)
    fb, _fct = split_fabric(fab_bytes)
    by_host = group_by_host(fb)
    ranked = top_by_peak_depth(by_host, 1)
    if not ranked:
        return
    host = ranked[0]
    recs = by_host[host]
    peak = max(r[3] for r in recs)
    soj = max(r[5] for r in recs) / 1e6
    names = _host_names(_processed_config(data_dir))
    label = names[host] if 0 <= host < len(names) else f"h{host}"
    print(f"  hottest queue: {label} (router inbound peaked at "
          f"{peak} packets, {soj:.2f} ms head sojourn)", file=out)


def _kern_hints(data_dir: str, stats: dict, out) -> None:
    """Device-kernel observatory joins for `trace explain`:

    - speculative-window waste — when the rollback ledger (aborted
      dispatch wall + forced re-exports) exceeds ~10% of a family's
      device dispatch wall, name the dominant abort kind and the
      remediation;
    - overlap stall — when the overlapped pipeline's measured
      device-idle fraction exceeds 25%, the double buffer is not
      hiding the host work: point at the svc plane drains and span
      codec wall that must fit inside the in-flight window
      (ISSUE 16);
    - low lane occupancy — on a device-routed run, name the stages
      whose occupancy sits under ~5% and the likeliest config
      remediation (tiny dev_span_K keeps spans short and lanes idle;
      a mixed-family fleet splits lanes across kernels)."""
    from shadow_tpu.trace.kernstat import DISPATCH_KEYS
    dispatch = stats.get("metrics", {}).get("wall", {}).get(
        "dispatch", {})
    for fam in DISPATCH_KEYS.values():
        d = dispatch.get(f"device_span_{fam}") or {}
        wall = float(d.get("dispatch_wall_s", 0.0))
        waste = float(d.get("rollback_wall_s", 0.0)) \
            + float(d.get("rollback_reexport_wall_s", 0.0))
        if wall > 0 and waste > 0.1 * wall:
            kinds = d.get("abort_kinds") or {}
            top = max(kinds, key=kinds.get) if kinds else "abort"
            label = {"struct": "AB_STRUCT (domain departure)",
                     "exchange-capacity": "AB_EXCH (exchange "
                     "capacity)"}.get(top, f"capacity ({top})")
            print(f"  speculative-window waste [{fam}]: "
                  f"{100.0 * waste / wall:.0f}% of the device "
                  f"dispatch wall rolled back unused "
                  f"({d.get('rolled_back_rounds', 0)} rounds; "
                  f"dominant abort: {label}).  Shrink the "
                  f"speculation pressure (smaller initial dev_span_K)"
                  f" or pre-size the aborting capacity "
                  f"(tpu_exchange_capacity / ring caps) so spans "
                  f"commit first try.", file=out)
        ov = d.get("overlap") or {}
        if ov.get("windows") and \
                float(ov.get("device_idle_frac", 0.0)) > 0.25:
            print(f"  overlap stall [{fam}]: device idle "
                  f"{100.0 * float(ov['device_idle_frac']):.0f}% of "
                  f"the pipelined wall — pipeline not overlapping — "
                  f"check svc plane workers / codec wall (the host-"
                  f"side drains and span codec conversion must fit "
                  f"inside the in-flight window), or raise "
                  f"dev_span_k_init so each window is long enough to "
                  f"hide the host work.", file=out)
    ks_bytes = _kern_bytes(data_dir)
    if not ks_bytes:
        return
    from shadow_tpu.trace.kernstat import (family_label,
                                           family_totals,
                                           low_occupancy_stages)
    for family, ent in sorted(family_totals(ks_bytes).items()):
        low = low_occupancy_stages(ent)
        if not low:
            continue
        worst = min(low, key=lambda kv: kv[1])
        spans = max(ent["spans"], 1)
        fam = family_label(family)
        print(f"  low lane occupancy [{fam}]: stage "
              f"'{worst[0]}' ran at {worst[1] / 10:.1f}% of its "
              f"{ent['hosts']}-lane width "
              f"({len(low)} stage(s) under 5%).  Likeliest "
              f"remediations: larger spans amortize idle iterations "
              f"(rounds/span is {ent['rounds'] // spans} — a tiny "
              f"dev_span_K or frequent boundaries keeps it low), or "
              f"the fleet mixes families so each kernel sees only "
              f"part of the host axis.", file=out)


def explain_report(data_dir: str, out=None) -> bool:
    """`trace explain`: top eligibility blockers -> remediation."""
    if out is None:
        out = sys.stdout
    stats, _sim, _wall, _tel, sc_bytes, fab_bytes = _load(data_dir)
    elig = stats.get("metrics", {}).get("wall", {}).get(
        "eligibility", {})
    rounds = stats.get("rounds", 0)
    if not elig:
        print("no eligibility block in sim-stats.json (pre-trace "
              "artifact?)", file=out)
        return False

    # Offending hosts per object-path cause, from the processed
    # config written next to sim-stats.json.
    pcap_hosts, cpu_hosts, other_hosts = [], [], []
    cfg = _processed_config(data_dir)
    for name, h in sorted((cfg.get("hosts") or {}).items()):
        if (h or {}).get("pcap_enabled"):
            pcap_hosts.append(name)
    if (cfg.get("experimental") or {}).get("host_cpu_threshold"):
        cpu_hosts = _host_names(cfg)
    hosts_of = {"object-path:pcap": pcap_hosts,
                "object-path:cpu-model": cpu_hosts,
                "object-path:other": other_hosts}

    device = elig.get("device-span", 0)
    print(f"device-span coverage: {device}/{rounds} rounds; top "
          f"blockers and remediation:", file=out)
    shown = 0
    managed_shown = False
    for name, n in sorted(elig.items(), key=lambda kv: -kv[1]):
        if name == "device-span":
            continue
        hint = _EXPLAIN.get(name)
        hosts = ", ".join(hosts_of.get(name, [])[:8]) or "(see config)"
        text = (hint[0].format(hosts=hosts) if hint
                else "no registered remediation for this reason.")
        pct = 100.0 * n / rounds if rounds else 0.0
        print(f"  {name} — {n} rounds ({pct:.1f}%)", file=out)
        print(f"      {text}", file=out)
        if not managed_shown and name in (
                "object-path:other", "object-path:py-task",
                "per-round:callback-host", "per-round:scheduler",
                "engine-span:py-limit",
                "engine-span:managed-quiescent"):
            # These are the reasons managed processes cause: join the
            # audit with the syscall channel, print the quiescence
            # fraction and name the offenders.
            _managed_blockers(data_dir, sc_bytes, out, elig=elig,
                              rounds=rounds)
            managed_shown = True
        if name == "per-round:outbox" and fab_bytes:
            # Rounds stalled on outbox pressure: name the hottest
            # queue (audit join with the fabric channel).
            _hottest_queue(data_dir, fab_bytes, out)
        shown += 1
        if shown >= 6:
            break
    if not shown:
        print("  (every round ran on the device — nothing to "
              "remediate)", file=out)
    # Device-kernel observatory joins (ISSUE 15): speculative-window
    # waste + low lane occupancy, from the dispatch ledger and
    # kernel-sim.bin.
    _kern_hints(data_dir, stats, out)
    return True


def run_config(config_path: str, data_dir: str | None = None) -> str:
    """Run a YAML config with the flight recorder forced on; returns
    the data directory."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation

    config = ConfigOptions.from_file(config_path)
    config.experimental.flight_recorder = "on"
    if data_dir is not None:
        config.general.data_directory = data_dir
    _manager, summary = run_simulation(config, write_data=True)
    if not summary.ok:
        for err in summary.plugin_errors:
            print(f"[trace] plugin error: {err}", file=sys.stderr)
    return config.general.data_directory


def smoke_managed() -> int:
    """Managed-process smoke leg: one real C binary under the shim
    with the syscall observatory on — disposition conservation must
    hold (trace sys exits ok) and the Chrome export must carry a
    non-empty per-process syscall counter track.  Skips cleanly when
    no C toolchain is available."""
    import shutil
    import subprocess
    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation

    if shutil.which("cc") is None:
        print("trace smoke: managed leg skipped (no C toolchain)",
              file=sys.stderr)
        return 0
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tests",
        "plugins", "sleep_time.c")
    with tempfile.TemporaryDirectory() as td:
        exe = os.path.join(td, "sleep_time")
        subprocess.run(["cc", "-O1", "-o", exe, src], check=True)
        base = os.path.join(td, "managed-smoke")
        config = ConfigOptions.from_yaml_text(f"""
general: {{ stop_time: 5s, seed: 3, data_directory: "{base}" }}
network:
  graph:
    type: gml
    inline: |
      graph [ node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" ] ]
experimental:
  strace_logging_mode: deterministic
  syscall_observatory: "on"
  flight_recorder: "on"
hosts:
  h0:
    network_node_id: 0
    processes:
      - {{ path: {exe}, start_time: 1s }}
""")
        _manager, summary = run_simulation(config, write_data=True)
        if not summary.ok:
            print(f"trace smoke: managed sim failed: "
                  f"{summary.plugin_errors}", file=sys.stderr)
            return 1
        if not sys_report(base):
            print("trace smoke: syscall dispositions do not conserve",
                  file=sys.stderr)
            return 1
        from shadow_tpu.trace.chrome import PID_SYSCALL, chrome_trace
        _stats, sim_bytes, wall, _tel, sc_bytes, _fab = _load(base)
        doc = chrome_trace(sim_bytes, wall, b"", sc_bytes)
        counters = [e for e in doc["traceEvents"]
                    if e.get("ph") == "C" and e.get("pid") == PID_SYSCALL]
        if not counters:
            print("trace smoke: chrome export has no per-process "
                  "syscall counter track", file=sys.stderr)
            return 1
    print(f"trace smoke: managed leg ok (dispositions conserved, "
          f"{len(counters)} syscall counter events)")
    return 0


def smoke_kern() -> int:
    """Device-kernel observatory smoke leg: an 8-host PHOLD fleet
    with forced device spans and the observatory on — the per-stage
    counters must conserve against micro_iters (`trace kern` exits
    ok, with a non-empty table) and the Chrome export must carry a
    non-empty per-stage counter track."""
    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation
    from shadow_tpu.tools.netgen import phold_yaml

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "kern-smoke")
        text = phold_yaml(8, n_init=2, mean_delay_ns=20_000_000,
                          stop_time="1s", seed=13, scheduler="tpu",
                          device_spans="force")
        config = ConfigOptions.from_yaml_text(text)
        config.experimental.kernel_observatory = "on"
        config.experimental.flight_recorder = "on"
        config.general.data_directory = base
        _manager, summary = run_simulation(config, write_data=True)
        if not summary.ok:
            print(f"trace smoke: kern sim failed: "
                  f"{summary.plugin_errors}", file=sys.stderr)
            return 1
        ks = _kern_bytes(base)
        if not ks:
            print("trace smoke: kernel observatory recorded nothing "
                  "(device spans never committed?)", file=sys.stderr)
            return 1
        if not kern_report(base):
            print("trace smoke: kernel-channel conservation violated",
                  file=sys.stderr)
            return 1
        from shadow_tpu.trace.chrome import PID_KERN, chrome_trace
        _stats, sim_bytes, wall, _tel, _sc, _fab = _load(base)
        doc = chrome_trace(sim_bytes, wall, ks_bytes=ks)
        counters = [e for e in doc["traceEvents"]
                    if e.get("ph") == "C" and e.get("pid") == PID_KERN]
        if not counters:
            print("trace smoke: chrome export has no per-stage kernel "
                  "counter track", file=sys.stderr)
            return 1
    print(f"trace smoke: kern leg ok (fires conserve, "
          f"{len(counters)} stage counter events)")
    return 0


def smoke(n_hosts: int) -> int:
    """50-host traced tgen TCP tier: summary + eligibility must
    render and account for every round, the drop-cause counters must
    conserve, and the Chrome export must carry a non-empty
    per-connection counter track (the ./setup trace target).  A
    managed-process leg (one real binary under the shim, syscall
    observatory on) rides along when a C toolchain is available."""
    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation
    from shadow_tpu.tools.netgen import tcp_stream_yaml

    with tempfile.TemporaryDirectory() as td:
        base = os.path.join(td, "trace-smoke")
        # Default nbytes keeps every client mid-stream at stop_time
        # (the generator's expected_final_state is `running`).
        text = tcp_stream_yaml(n_hosts, loss=0.005, stop_time="2s",
                               seed=11, scheduler="tpu")
        config = ConfigOptions.from_yaml_text(text)
        config.experimental.flight_recorder = "on"
        config.experimental.sim_netstat = "on"
        config.experimental.sim_fabricstat = "on"
        config.general.data_directory = base
        _manager, summary = run_simulation(config, write_data=True)
        if not summary.ok:
            print(f"trace smoke: sim failed: {summary.plugin_errors}",
                  file=sys.stderr)
            return 1
        chrome_out = os.path.join(base, "chrome-trace.json")
        ok = summarize(base, chrome_out=chrome_out)
        if not ok:
            print("trace smoke: eligibility report did not account "
                  "for all rounds", file=sys.stderr)
            return 1
        if not net_report(base):
            print("trace smoke: drop-cause counters do not conserve",
                  file=sys.stderr)
            return 1
        if not fabric_report(base):
            print("trace smoke: fabric byte-conservation violated",
                  file=sys.stderr)
            return 1
        fct_report(base)
        explain_report(base)
        with open(chrome_out) as f:
            doc = json.load(f)
        slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        if not slices:
            print("trace smoke: chrome export has no slices",
                  file=sys.stderr)
            return 1
        counters = [e for e in doc["traceEvents"]
                    if e.get("ph") == "C"]
        if not counters:
            print("trace smoke: chrome export has no sim-netstat "
                  "counter track", file=sys.stderr)
            return 1
        from shadow_tpu.trace.chrome import PID_FABRIC
        fab_counters = [e for e in doc["traceEvents"]
                        if e.get("ph") == "C"
                        and e.get("pid") == PID_FABRIC]
        if not fab_counters:
            print("trace smoke: chrome export has no per-link fabric "
                  "counter track", file=sys.stderr)
            return 1
    print(f"trace smoke: ok ({n_hosts} hosts, {summary.rounds} rounds "
          f"fully attributed, drops conserved, "
          f"{len(counters)} counter events)")
    rc = smoke_kern()
    if rc:
        return rc
    return smoke_managed()


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] in ("net", "explain", "sys", "fabric", "fct",
                            "kern"):
        # Subcommands: `trace net DATA_DIR [--top N]`,
        #              `trace sys DATA_DIR [--top N]`,
        #              `trace fabric DATA_DIR [--top N]`,
        #              `trace fct DATA_DIR`,
        #              `trace kern DATA_DIR`,
        #              `trace explain DATA_DIR`.
        sub = argparse.ArgumentParser(
            prog=f"shadow_tpu.tools.trace {argv[0]}")
        sub.add_argument("data_dir")
        if argv[0] in ("net", "sys", "fabric"):
            sub.add_argument("--top", type=int, default=10,
                             help="rows in the report (default 10)")
        sargs = sub.parse_args(argv[1:])
        from shadow_tpu.utils.platform import honor_platform_env
        honor_platform_env()
        if argv[0] == "net":
            return 0 if net_report(sargs.data_dir,
                                   top_n=sargs.top) else 1
        if argv[0] == "sys":
            return 0 if sys_report(sargs.data_dir,
                                   top_n=sargs.top) else 1
        if argv[0] == "fabric":
            return 0 if fabric_report(sargs.data_dir,
                                      top_n=sargs.top) else 1
        if argv[0] == "fct":
            return 0 if fct_report(sargs.data_dir) else 1
        if argv[0] == "kern":
            return 0 if kern_report(sargs.data_dir) else 1
        return 0 if explain_report(sargs.data_dir) else 1

    ap = argparse.ArgumentParser(prog="shadow_tpu.tools.trace",
                                 description=__doc__)
    ap.add_argument("data_dir", nargs="?",
                    help="data directory of a flight-recorded run")
    ap.add_argument("--run", metavar="CONFIG",
                    help="run this YAML config with the flight "
                         "recorder on, then summarize")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the 50-host traced smoke sim and exit "
                         "nonzero unless the report renders")
    ap.add_argument("--hosts", type=int, default=50,
                    help="host count for --smoke (default 50)")
    args = ap.parse_args(argv)

    from shadow_tpu.utils.platform import honor_platform_env
    honor_platform_env()

    if args.smoke:
        return smoke(args.hosts)
    if args.run is not None:
        data_dir = run_config(args.run, args.data_dir)
    elif args.data_dir is not None:
        data_dir = args.data_dir
    else:
        ap.print_usage(sys.stderr)
        print("trace: a data directory, --run, or --smoke is required",
              file=sys.stderr)
        return 2
    ok = summarize(data_dir, chrome_out=args.chrome)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
