"""One-shot single-host runner — the `shadow-exec` equivalent.

Ref: shadowtools/src/shadowtools/shadow_exec.py.  Runs one command
under the simulator on a single 1 Gbit host and relays its stdout/
stderr and exit code, so quick determinism experiments don't need a
YAML file:

    python -m shadow_tpu.tools.exec -- /bin/date
    python -m shadow_tpu.tools.exec --stop-time 30s -- ./my_binary arg
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="shadow-exec",
        description="run one command under the simulator")
    parser.add_argument("--stop-time", default="1h")
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--keep", metavar="DIR",
                        help="keep the data directory at DIR")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- command [args...]")
    args = parser.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given")

    exe = cmd[0]
    if "/" not in exe:
        # Internal app names pass through; external commands resolve on
        # PATH here, explicitly (the simulator itself never searches
        # PATH — a typo must not run an unrelated binary).
        import shutil
        resolved = shutil.which(exe)
        if resolved is not None:
            exe = resolved

    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import run_simulation
    from shadow_tpu.tools import one_host_config

    cfg_dict = one_host_config(exe, cmd[1:], stop_time=args.stop_time,
                               seed=args.seed)
    data_dir = args.keep or tempfile.mkdtemp(prefix="shadow-exec-")
    cfg_dict["general"]["data_directory"] = data_dir
    config = ConfigOptions.from_dict(dict(cfg_dict))
    manager, summary = run_simulation(config, write_data=bool(args.keep))

    host = manager.hosts[0]
    proc = next(iter(host.processes.values()))
    sys.stdout.buffer.write(bytes(proc.stdout))
    sys.stdout.flush()
    sys.stderr.buffer.write(bytes(proc.stderr))
    sys.stderr.flush()
    if not args.keep:
        import shutil as _sh
        _sh.rmtree(data_dir, ignore_errors=True)
    if not summary.ok:
        for err in summary.plugin_errors:
            print(f"[shadow-exec] {err}", file=sys.stderr)
        return 1
    if proc.exit_code is None:
        # Never exited (deadlock / ran past stop_time).
        print("[shadow-exec] process still running at stop_time",
              file=sys.stderr)
        return 1
    return proc.exit_code


if __name__ == "__main__":
    sys.exit(main())
