"""Fabric-surrogate CLI (docs/SWEEP.md "Surrogate").

    python -m shadow_tpu.tools.surrogate train DATASET.swds \
        --out MODEL.npz [--holdout fan_in:16] [--steps 300] [--seed 1]
    python -m shadow_tpu.tools.surrogate eval MODEL.npz DATASET.swds \
        [--holdout fan_in:16]

`train` fits the RouteNet-shaped GNN on every point NOT matched by
the holdout predicate (`feature:min` — points with feature >= min
are held out) and, when a holdout is given, prints the surrogate-vs-
simulator per-quantile error table on the held-out fabrics.  `eval`
reloads a saved model and re-renders the table — honest numbers
either way, large errors included.
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_holdout(text: str | None):
    if text is None:
        return None
    try:
        feature, min_s = text.split(":")
        return feature, float(min_s)
    except ValueError:
        raise SystemExit(f"surrogate: --holdout must be "
                         f"feature:min, got {text!r}")


def print_error_table(tab: dict, out=None) -> None:
    out = out or sys.stdout
    print("surrogate-vs-simulator (held-out fabrics):", file=out)
    print(f"  {'point':<28} {'flows':>6} "
          f"{'p50 err':>8} {'p99 err':>8} {'p999 err':>9} "
          f"{'peak err':>9}", file=out)
    for r in tab["points"]:
        print(f"  {r['point_id'][:28]:<28} {r['flows']:>6} "
              f"{r['rel_err_p50']:>8.1%} {r['rel_err_p99']:>8.1%} "
              f"{r['rel_err_p999']:>9.1%} "
              f"{r.get('rel_err_peak', float('nan')):>9.1%}",
              file=out)
    print(f"  mean: p50 {tab['mean_rel_err_p50']:.1%}, "
          f"p99 {tab['mean_rel_err_p99']:.1%}, "
          f"p999 {tab['mean_rel_err_p999']:.1%}", file=out)


def cmd_train(args) -> int:
    from shadow_tpu.sweep import dataset
    from shadow_tpu.surrogate import features, model, train
    ds = dataset.load(args.dataset)
    samples = features.build_samples(ds)
    holdout = _parse_holdout(args.holdout)
    if holdout:
        tr, held = train.split_samples(samples, *holdout)
    else:
        tr, held = samples, []
    if not tr:
        print("surrogate: holdout leaves no training points",
              file=sys.stderr)
        return 1
    params, hist = train.train(
        tr, seed=args.seed, steps=args.steps,
        log=lambda m: print(m, file=sys.stderr))
    meta = {
        "dataset": ds.meta["name"],
        "seed": args.seed,
        "steps": args.steps,
        "loss_first": round(hist[0], 6),
        "loss_last": round(hist[-1], 6),
        "holdout": args.holdout,
        "trained_points": [s["point_id"] for s in tr],
    }
    print(f"trained on {len(tr)} point(s); loss "
          f"{hist[0]:.4f} -> {hist[-1]:.4f}")
    if held:
        tab = train.error_table(params, held)
        meta["error_table"] = tab
        print_error_table(tab)
    if args.out:
        model.save(args.out, params, meta)
        print(f"model: {args.out}")
    return 0


def cmd_eval(args) -> int:
    from shadow_tpu.sweep import dataset
    from shadow_tpu.surrogate import features, model, train
    params, meta = model.load(args.model)
    ds = dataset.load(args.dataset)
    samples = features.build_samples(ds)
    holdout = _parse_holdout(args.holdout or meta.get("holdout"))
    if holdout:
        trained = set(meta.get("trained_points", []))
        _tr, held = train.split_samples(samples, *holdout)
        leak = [s["point_id"] for s in held
                if s["point_id"] in trained]
        if leak:
            print(f"surrogate: refusing to evaluate — held-out "
                  f"point(s) were in the training set: {leak[:4]}",
                  file=sys.stderr)
            return 1
    else:
        held = samples
    if not held:
        print("surrogate: nothing to evaluate", file=sys.stderr)
        return 1
    tab = train.error_table(params, held)
    print_error_table(tab)
    print(json.dumps({k: v for k, v in tab.items()
                      if k != "points"}, sort_keys=True))
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    ap = argparse.ArgumentParser(prog="shadow_tpu.tools.surrogate",
                                 description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    tr.add_argument("dataset")
    tr.add_argument("--out")
    tr.add_argument("--holdout")
    tr.add_argument("--steps", type=int, default=300)
    tr.add_argument("--seed", type=int, default=1)
    ev = sub.add_parser("eval")
    ev.add_argument("model")
    ev.add_argument("dataset")
    ev.add_argument("--holdout")
    args = ap.parse_args(argv)
    from shadow_tpu.utils.platform import honor_platform_env
    honor_platform_env()
    from shadow_tpu.sweep.dataset import DatasetError
    try:
        if args.cmd == "train":
            return cmd_train(args)
        return cmd_eval(args)
    except (DatasetError, ValueError) as e:
        print(f"surrogate: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
