"""Deterministic network/workload config generation.

The reference ships tornettools/tgen-generated YAML for its scale
configs (SURVEY.md section 6); this module is the in-tree equivalent
used by the multi-chip dry run, the mesh-scheduler tests, and bench.py's
BASELINE configs — everything is derived from (n_hosts, seed) with pure
integer arithmetic so two processes generate byte-identical configs.
"""

from __future__ import annotations


def full_mesh_gml(n_nodes: int, bw: str = "100 Mbit",
                  base_latency_us: int = 2000, step_us: int = 500,
                  loss: float = 0.02) -> str:
    """Fully-connected GML graph with varied latencies and a sprinkling
    of lossy edges (every edge with (i+j) % 5 == 0), plus self-edges."""
    lines = ["graph [ directed 0"]
    for i in range(n_nodes):
        lines.append(f'  node [ id {i} host_bandwidth_down "{bw}" '
                     f'host_bandwidth_up "{bw}" ]')
    for i in range(n_nodes):
        lines.append(f'  edge [ source {i} target {i} latency "500 us" ]')
    for i in range(n_nodes):
        for j in range(i + 1, n_nodes):
            lat = base_latency_us + ((i * 7 + j * 13) % 17) * step_us
            lossy = f" packet_loss {loss}" if loss and (i + j) % 5 == 0 else ""
            lines.append(f'  edge [ source {i} target {j} '
                         f'latency "{lat} us"{lossy} ]')
    lines.append("]")
    return "\n".join(lines)


def three_tier_gml(n_core: int = 4, n_mid: int = 8, n_leaf: int = 40,
                   loss: float = 0.01) -> str:
    """BASELINE config 3's '3-tier latency/loss graph': core routers in
    a full mesh (low latency, high bw), mid-tier nodes homed on cores,
    leaf nodes homed on mids (the tier hosts attach to)."""
    lines = ["graph [ directed 0"]
    nid = 0
    cores = []
    for i in range(n_core):
        lines.append(f'  node [ id {nid} host_bandwidth_down "10 Gbit" '
                     f'host_bandwidth_up "10 Gbit" ]')
        cores.append(nid)
        nid += 1
    mids = []
    for i in range(n_mid):
        lines.append(f'  node [ id {nid} host_bandwidth_down "1 Gbit" '
                     f'host_bandwidth_up "1 Gbit" ]')
        mids.append(nid)
        nid += 1
    leaves = []
    for i in range(n_leaf):
        lines.append(f'  node [ id {nid} host_bandwidth_down "100 Mbit" '
                     f'host_bandwidth_up "50 Mbit" ]')
        leaves.append(nid)
        nid += 1
    for n in cores + mids + leaves:
        lines.append(f'  edge [ source {n} target {n} latency "200 us" ]')
    for a in range(n_core):
        for b in range(a + 1, n_core):
            lat = 2000 + ((a * 3 + b) % 5) * 1000
            lines.append(f'  edge [ source {cores[a]} target {cores[b]} '
                         f'latency "{lat} us" ]')
    for i, m in enumerate(mids):
        lat = 5000 + (i % 4) * 2500
        lines.append(f'  edge [ source {m} target {cores[i % n_core]} '
                     f'latency "{lat} us" ]')
    for i, lf in enumerate(leaves):
        lat = 10000 + (i % 8) * 3000
        lossy = f" packet_loss {loss}" if loss and i % 4 == 0 else ""
        lines.append(f'  edge [ source {lf} target {mids[i % n_mid]} '
                     f'latency "{lat} us"{lossy} ]')
    lines.append("]")
    return "\n".join(lines)


def _indent(text: str, pad: str) -> str:
    return "\n".join(pad + line for line in text.splitlines())


def _tcp_line(tcp: dict | None) -> str:
    """One per-host `tcp:` block line (or nothing): every TCP
    generator threads this through so any workload can run under
    either congestion controller — e.g. tcp={"cc": "dctcp",
    "ecn": "on"}."""
    if not tcp:
        return ""
    cc = tcp.get("cc", "reno")
    ecn = tcp.get("ecn", "off")
    if isinstance(ecn, bool):
        ecn = "on" if ecn else "off"
    return f"    tcp: {{ cc: {cc}, ecn: {ecn} }}\n"


def udp_mesh_yaml(n_hosts: int, n_nodes: int = 8, floods_per_host: int = 3,
                  count: int = 6, size: int = 600, stop_time: str = "10s",
                  seed: int = 1, scheduler: str = "serial",
                  experimental_extra: dict | None = None,
                  gml: str | None = None, pcap_hosts: int = 0,
                  object_hosts: int = 0,
                  data_directory: str | None = None) -> str:
    """N-host UDP traffic mesh: every host runs one udp-sink (runs until
    sim end) and `floods_per_host` udp-flood senders at staggered starts.
    Final process states are loss-independent (floods always exit 0), so
    the byte-diff gate is the packet trace alone."""
    if gml is None:
        gml = full_mesh_gml(n_nodes)
    exp_lines = [f"  scheduler: {scheduler}"]
    for k, v in (experimental_extra or {}).items():
        exp_lines.append(f"  {k}: {v}")
    names = [f"host{i:05d}" for i in range(n_hosts)]
    base_offsets = (1, 5, 11, 23, 47, 95)
    if floods_per_host > len(base_offsets):
        raise ValueError(f"floods_per_host > {len(base_offsets)} "
                         f"not supported (got {floods_per_host})")
    offsets = base_offsets[:floods_per_host]
    host_blocks = []
    for i, name in enumerate(names):
        procs = [f'      - {{ path: udp-sink, args: ["9000"], '
                 f'expected_final_state: running }}']
        for k, off in enumerate(offsets):
            peer = names[(i + off) % n_hosts]
            start_ms = 1000 + ((i * 31 + k * 157) % 1000)
            procs.append(
                f'      - {{ path: udp-flood, '
                f'args: [{peer}, "9000", "{count}", "{size}"], '
                f'start_time: {start_ms} ms }}')
        extra_opts = ""
        if i < pcap_hosts:
            extra_opts += "    pcap_enabled: true\n"
        if i < object_hosts:
            extra_opts += "    native_dataplane: false\n"
        host_blocks.append(
            f"  {name}:\n    network_node_id: {i % n_nodes}\n"
            + extra_opts + f"    processes:\n" + "\n".join(procs))
    datadir = (f', data_directory: "{data_directory}"'
               if data_directory else "")
    return (f"general: {{ stop_time: {stop_time}, seed: {seed}{datadir} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp_lines) + "\n"
            f"hosts:\n" + "\n".join(host_blocks) + "\n")


def phold_args(i: int, names: list[str], n_init: int,
               mean_delay_ns: int,
               peers_per_host: int | None = None) -> list[str]:
    """One PHOLD LP's argv — the single source of the peer law
    (next-k ring neighbors, full mesh by default) and the phold arg
    layout, shared by phold_yaml and the bench dict builders."""
    n = len(names)
    if peers_per_host is not None:
        k = min(peers_per_host, n - 1)
        peers = [names[(i + 1 + j) % n] for j in range(k)]
    else:
        peers = [p for p in names if p != names[i]]
    return ["7000", str(i), str(n_init), str(mean_delay_ns)] + peers


def phold_yaml(n_hosts: int, n_init: int = 3,
               mean_delay_ns: int = 20_000_000, stop_time: str = "2s",
               seed: int = 13, scheduler: str = "serial",
               device_spans: str | None = None,
               bandwidth: str = "1 Gbit", latency: str = "5 ms",
               peers_per_host: int | None = None) -> str:
    """Classic PHOLD (ref: src/test/phold): every host one LP bouncing
    messages to pseudo-random peers after pseudo-exponential holds.
    peers_per_host bounds each LP's peer list to its next-k ring
    neighbors (full mesh by default) — above ~10k LPs a full n^2 peer
    matrix no longer fits anything."""
    names = [f"lp{i:04d}" for i in range(n_hosts)]
    blocks = []
    for i, name in enumerate(names):
        args = " ".join(phold_args(i, names, n_init, mean_delay_ns,
                                   peers_per_host))
        blocks.append(
            f"  {name}:\n    network_node_id: 0\n    processes:\n"
            f'      - {{ path: phold, args: "{args}", '
            f"start_time: 100ms, "
            f"expected_final_state: running }}")
    exp = [f"  scheduler: {scheduler}"]
    if device_spans is not None:
        exp.append(f"  tpu_device_spans: {device_spans}")
    gml = (f'graph [ node [ id 0 host_bandwidth_down "{bandwidth}" '
           f'host_bandwidth_up "{bandwidth}" ] '
           f'edge [ source 0 target 0 latency "{latency}" ] ]')
    return (f"general: {{ stop_time: {stop_time}, seed: {seed} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp) + "\n"
            f"hosts:\n" + "\n".join(blocks) + "\n")


def mesh_family_yaml(n_hosts: int, count: int = 30, size: int = 400,
                     bw_down: str = "1 Mbit", bw_up: str = "1 Mbit",
                     loss: float = 0.02, latency: str = "10 ms",
                     sbuf: str = "8 KiB", seed: int = 29,
                     stop_time: str = "30s", scheduler: str = "serial",
                     device_spans: str | None = None) -> str:
    """Paced udp-mesh: every host ONE udp-mesh process (main sink +
    sender thread over a shared bound socket), bandwidth-paced so the
    sim spans many windows — the device-span mesh-family workload
    (tests/test_phold_span.py and the multichip dryrun share it)."""
    names = [f"m{i:02d}" for i in range(n_hosts)]
    blocks = []
    for name in names:
        peers = " ".join(p for p in names if p != name)
        blocks.append(
            f"  {name}:\n    network_node_id: 0\n    processes:\n"
            f'      - {{ path: udp-mesh, args: "9000 {count} {size} '
            f'{peers}", start_time: 100ms, '
            f"expected_final_state: any }}")
    exp = [f"  scheduler: {scheduler}",
           f"  socket_send_buffer: {sbuf}"]
    if device_spans is not None:
        exp.append(f"  tpu_device_spans: {device_spans}")
    loss_s = f" packet_loss {loss}" if loss else ""
    gml = (f'graph [ node [ id 0 host_bandwidth_down "{bw_down}" '
           f'host_bandwidth_up "{bw_up}" ] '
           f'edge [ source 0 target 0 latency "{latency}"{loss_s} ] ]')
    return (f"general: {{ stop_time: {stop_time}, seed: {seed} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp) + "\n"
            f"hosts:\n" + "\n".join(blocks) + "\n")


def tcp_stream_yaml(n_hosts: int, n_servers: int | None = None,
                    nbytes: int = 50_000_000, loss: float = 0.01,
                    latency: str = "10 ms", bw_down: str = "50 Mbit",
                    bw_up: str = "50 Mbit", stop_time: str = "4s",
                    seed: int = 11, scheduler: str = "serial",
                    device_spans: str | None = None,
                    tcp: dict | None = None) -> str:
    """Fixed-connection TCP streaming tier: every client opens ONE
    connection (count=1, synchronized starts, no accept churn) and the
    transfer is sized to still be streaming at stop_time — so after the
    handshake prefix the whole sim is steady-state bulk transfer:
    cwnd/ssthresh dynamics, SACK, RTO and delack/persist timers on a
    lossy edge.  This is the TCP device-span family's workload
    (ops/tcp_span.py; the multichip dryrun and bench[tcp-dev] rungs).
    Buffer autotuning is off so windows — and with them the SoA ring
    caps — stay bounded."""
    if n_servers is None:
        n_servers = max(1, n_hosts // 8)
    names = [f"srv{i:03d}" for i in range(n_servers)]
    loss_s = f" packet_loss {loss}" if loss else ""
    gml = (f'graph [ node [ id 0 host_bandwidth_down "{bw_down}" '
           f'host_bandwidth_up "{bw_up}" ] '
           f'edge [ source 0 target 0 latency "{latency}"{loss_s} ] ]')
    tl = _tcp_line(tcp)
    blocks = []
    for name in names:
        blocks.append(
            f"  {name}:\n    network_node_id: 0\n{tl}    processes:\n"
            f'      - {{ path: tgen-server, args: ["8080"], '
            f"expected_final_state: running }}")
    for i in range(n_hosts - n_servers):
        server = names[i % n_servers]
        blocks.append(
            f"  cli{i:04d}:\n    network_node_id: 0\n{tl}    processes:\n"
            f'      - {{ path: tgen-client, '
            f'args: [{server}, "8080", "{nbytes}", "1"], '
            f"start_time: 100ms, expected_final_state: running }}")
    exp = [f"  scheduler: {scheduler}",
           "  socket_send_autotune: false",
           "  socket_recv_autotune: false"]
    if device_spans is not None:
        exp.append(f"  tpu_device_spans: {device_spans}")
    return (f"general: {{ stop_time: {stop_time}, seed: {seed} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp) + "\n"
            f"hosts:\n" + "\n".join(blocks) + "\n")


def compile_echo_binaries(out_dir: str) -> dict | None:
    """Build the managed-fleet C plugins (udp echo server/client) into
    `out_dir`; returns {name: path} or None without a C toolchain.
    One home for the compile step — bench's managed rungs and
    `./setup managed` all feed managed_fleet_yaml from it."""
    import os
    import shutil
    import subprocess
    if shutil.which("cc") is None:
        return None
    plug = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "tests", "plugins")
    bins = {}
    for name in ("udp_echo_server", "udp_echo_client"):
        out = os.path.join(out_dir, name)
        subprocess.run(["cc", "-O1", "-o", out,
                        os.path.join(plug, name + ".c")], check=True)
        bins[name] = out
    return bins


def managed_fleet_yaml(server_bin: str, client_bin: str, n_procs: int,
                       stop_time: str = "30s", seed: int = 3) -> str:
    """N-process managed (real-binary) fleet: one C UDP echo server
    per 16 processes, the rest clients (the managed-1k/10k bench
    rungs and `./setup managed` share it, ISSUE 13).  Servers get
    EXPLICIT ip_addr so clients can target them at any fleet size —
    the auto-assignment pool skips .0/.255 octets and is not
    arithmetic — and each server's echo budget counts exactly the
    clients its `i % n_servers` slot serves (an over-counted server
    would wait forever, an under-counted one would exit early and
    strand its last client)."""
    n_servers = max(1, n_procs // 16)
    n_clients = n_procs - n_servers
    blocks = []
    for i in range(n_servers):
        served = n_clients // n_servers + (1 if i < n_clients
                                           % n_servers else 0)
        blocks.append(f"""
  srv{i:04d}:
    network_node_id: 0
    ip_addr: 11.200.{i // 250}.{i % 250 + 1}
    processes:
      - path: {server_bin}
        args: "9000 {3 * served}"
        start_time: 1s""")
    for i in range(n_clients):
        s = i % n_servers
        blocks.append(f"""
  cli{i:05d}:
    network_node_id: 0
    processes:
      - path: {client_bin}
        args: "11.200.{s // 250}.{s % 250 + 1} 9000 3 64"
        start_time: 2s""")
    return f"""
general:
  stop_time: {stop_time}
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
        edge [ source 0 target 0 latency "10 ms" ] ]
hosts:{''.join(blocks)}
"""


def incast_yaml(fan_in: int, nbytes: int = 500_000,
                server_bw: str = "20 Mbit", client_bw: str = "100 Mbit",
                latency: str = "2 ms", stop_time: str = "3s",
                seed: int = 17, scheduler: str = "serial",
                device_spans: str | None = None,
                tcp: dict | None = None) -> str:
    """Minimal N->1 fan-in (incast): ONE sink host runs `fan_in`
    tgen-client downloads — one from each of `fan_in` source servers —
    all opened at the SAME instant, with the sink's downlink as the
    shared bottleneck.  The N response streams converge on the sink's
    inbound router queue: the canonical queue-buildup smoke for the
    fabric observatory (CoDel depth climbs, head sojourn crosses the
    5 ms target, the control law drops, and every drop must reconcile
    in the byte-conservation sweep).  Thread tcp={"cc": "dctcp",
    "ecn": "on"} and the sink's queue MARKS instead: the
    `bench[incast-ecn-32]` rung runs exactly that side by side with
    this drop-based shape.  The rest of the datacenter pack lives in
    leaf_spine_yaml / rpc_burst_yaml below; this remains the stressor
    the fabric channel's conservation gate runs against
    (tests/test_fabricstat.py, tests/test_dctcp.py, `trace fabric`)."""
    gml_lines = ["graph [ directed 0",
                 f'  node [ id 0 host_bandwidth_down "{server_bw}" '
                 f'host_bandwidth_up "{server_bw}" ]',
                 f'  node [ id 1 host_bandwidth_down "{client_bw}" '
                 f'host_bandwidth_up "{client_bw}" ]',
                 f'  edge [ source 0 target 0 latency "{latency}" ]',
                 f'  edge [ source 1 target 1 latency "{latency}" ]',
                 f'  edge [ source 0 target 1 latency "{latency}" ]',
                 "]"]
    gml = "\n".join(gml_lines)
    sink_procs = []
    for i in range(fan_in):
        sink_procs.append(
            f'      - {{ path: tgen-client, '
            f'args: [src{i:03d}, "8080", "{nbytes}", "1"], '
            f"start_time: 100ms, expected_final_state: any }}")
    tl = _tcp_line(tcp)
    blocks = [f"  sink:\n    network_node_id: 0\n{tl}    processes:\n"
              + "\n".join(sink_procs)]
    for i in range(fan_in):
        blocks.append(
            f"  src{i:03d}:\n    network_node_id: 1\n{tl}    processes:\n"
            f'      - {{ path: tgen-server, args: ["8080"], '
            f"expected_final_state: running }}")
    exp = [f"  scheduler: {scheduler}",
           "  socket_send_autotune: false",
           "  socket_recv_autotune: false"]
    if device_spans is not None:
        exp.append(f"  tpu_device_spans: {device_spans}")
    return (f"general: {{ stop_time: {stop_time}, seed: {seed} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp) + "\n"
            f"hosts:\n" + "\n".join(blocks) + "\n")


def tgen_tier_yaml(n_hosts: int, n_servers: int | None = None,
                   nbytes: int = 100_000, count: int = 1,
                   stop_time: str = "60s", seed: int = 1,
                   scheduler: str = "serial",
                   experimental_extra: dict | None = None,
                   n_core: int = 4, n_mid: int = 8,
                   n_leaf: int = 40,
                   tcp: dict | None = None) -> str:
    """BASELINE config 3: tgen-style TCP transfers on the 3-tier graph.
    Servers live on mid-tier nodes; clients on leaves download
    `count` x `nbytes` from a deterministic server choice."""
    gml = three_tier_gml(n_core=n_core, n_mid=n_mid, n_leaf=n_leaf)
    if n_servers is None:
        n_servers = max(1, n_hosts // 50)
    exp_lines = [f"  scheduler: {scheduler}"]
    for k, v in (experimental_extra or {}).items():
        exp_lines.append(f"  {k}: {v}")
    blocks = []
    server_names = [f"server{i:03d}" for i in range(n_servers)]
    tl = _tcp_line(tcp)
    for i, name in enumerate(server_names):
        blocks.append(
            f"  {name}:\n    network_node_id: {n_core + (i % n_mid)}\n"
            f"{tl}    processes:\n"
            f'      - {{ path: tgen-server, args: ["8080"], '
            f'expected_final_state: running }}')
    n_clients = n_hosts - n_servers
    for i in range(n_clients):
        name = f"client{i:05d}"
        server = server_names[i % n_servers]
        node = n_core + n_mid + (i % n_leaf)
        start_ms = 1000 + (i * 37) % 5000
        blocks.append(
            f"  {name}:\n    network_node_id: {node}\n"
            f"{tl}    processes:\n"
            f'      - {{ path: tgen-client, '
            f'args: [{server}, "8080", "{nbytes}", "{count}"], '
            f'start_time: {start_ms} ms }}')
    return (f"general: {{ stop_time: {stop_time}, seed: {seed} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp_lines) + "\n"
            f"hosts:\n" + "\n".join(blocks) + "\n")


def leaf_spine_gml(n_leaf: int = 4, n_spine: int = 2,
                   spine_latency_us: int = 40,
                   rack_latency_us: int = 10,
                   leaf_bw: str = "1 Gbit",
                   spine_bw: str = "10 Gbit") -> str:
    """k-ary leaf-spine fabric on the existing graph/router layers:
    spine nodes first, then leaf (ToR) nodes, every leaf uplinked to
    every spine.  ECMP is modeled the way a hashed fabric behaves
    under shortest-path routing: each leaf->spine uplink's latency is
    perturbed by a small deterministic per-(leaf, spine) hash (sub-
    microsecond scale), so Dijkstra resolves each leaf PAIR onto the
    hash-minimal spine — flows spread across spines exactly like a
    5-tuple hash spreads them, and the choice is config-deterministic
    on every path.  Hosts attach to leaf nodes only."""
    lines = ["graph [ directed 0"]
    spines = list(range(n_spine))
    leaves = [n_spine + i for i in range(n_leaf)]
    for s in spines:
        lines.append(f'  node [ id {s} host_bandwidth_down "{spine_bw}" '
                     f'host_bandwidth_up "{spine_bw}" ]')
    for lf in leaves:
        lines.append(f'  node [ id {lf} host_bandwidth_down "{leaf_bw}" '
                     f'host_bandwidth_up "{leaf_bw}" ]')
    for lf in leaves:
        # intra-rack hop (host -> ToR -> host)
        lines.append(f'  edge [ source {lf} target {lf} '
                     f'latency "{rack_latency_us} us" ]')
    for i, lf in enumerate(leaves):
        for s in spines:
            # ECMP hash perturbation: 100 ns granularity, < 1 us total
            jitter = (i * 131 + s * 241) % 8
            lat_ns = spine_latency_us * 1000 + jitter * 100
            lines.append(f'  edge [ source {lf} target {s} '
                         f'latency "{lat_ns} ns" ]')
    lines.append("]")
    return "\n".join(lines)


def leaf_spine_yaml(n_leaf: int = 4, hosts_per_leaf: int = 4,
                    n_spine: int = 2, nbytes: int = 1_000_000,
                    count: int = 2, leaf_bw: str = "1 Gbit",
                    stop_time: str = "5s", seed: int = 23,
                    scheduler: str = "serial",
                    device_spans: str | None = None,
                    tcp: dict | None = None) -> str:
    """Cross-rack traffic on the ECMP-hashed leaf-spine fabric: the
    first host of every rack runs a tgen-server, every other host
    downloads from a deterministically-chosen server in a DIFFERENT
    rack — all flows cross the spine, so per-pair spine selection (the
    hash-perturbed shortest path) and the receiving racks' inbound
    queues carry the load.  Thread tcp={"cc": "dctcp", "ecn": "on"}
    to run the fabric under DCTCP."""
    if n_leaf < 2:
        raise ValueError("leaf_spine_yaml needs n_leaf >= 2 (every "
                         "client downloads cross-rack)")
    gml = leaf_spine_gml(n_leaf=n_leaf, n_spine=n_spine,
                         leaf_bw=leaf_bw)
    tl = _tcp_line(tcp)
    blocks = []
    for leaf in range(n_leaf):
        node = n_spine + leaf
        for i in range(hosts_per_leaf):
            name = f"r{leaf:02d}h{i:02d}"
            if i == 0:
                blocks.append(
                    f"  {name}:\n    network_node_id: {node}\n"
                    f"{tl}    processes:\n"
                    f'      - {{ path: tgen-server, args: ["8080"], '
                    f"expected_final_state: running }}")
            else:
                peer_leaf = (leaf + i) % n_leaf
                if peer_leaf == leaf:
                    peer_leaf = (leaf + 1) % n_leaf
                server = f"r{peer_leaf:02d}h00"
                start_ms = 100 + ((leaf * 37 + i * 13) % 50)
                blocks.append(
                    f"  {name}:\n    network_node_id: {node}\n"
                    f"{tl}    processes:\n"
                    f'      - {{ path: tgen-client, '
                    f'args: [{server}, "8080", "{nbytes}", "{count}"], '
                    f"start_time: {start_ms} ms, "
                    f"expected_final_state: any }}")
    exp = [f"  scheduler: {scheduler}",
           "  socket_send_autotune: false",
           "  socket_recv_autotune: false"]
    if device_spans is not None:
        exp.append(f"  tpu_device_spans: {device_spans}")
    return (f"general: {{ stop_time: {stop_time}, seed: {seed} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp) + "\n"
            f"hosts:\n" + "\n".join(blocks) + "\n")


def rpc_sizes(seed: int, n_clients: int, bursts: int, nbytes: int,
              size_law: str | None, size_shape: float = 1.5,
              size_sigma: float = 1.0,
              size_cap_factor: int = 20) -> list[list[int]]:
    """Deterministic per-(client, burst) RPC response sizes.

    `size_law=None` is the fixed-size legacy shape (every transfer
    exactly `nbytes`).  The heavy-tailed laws of arXiv 2205.01234's
    tail-estimation regimes draw from counter-based threefry keyed by
    (seed, client, burst) — order-independent, so two generator calls
    (and two campaign runs) produce byte-identical configs:

    - "pareto": Pareto(alpha=size_shape, xm scaled so the MEAN stays
      `nbytes`); requires alpha > 1 or the mean diverges — refused.
    - "lognormal": LogNormal(sigma=size_sigma, mu chosen so the MEAN
      stays `nbytes`); requires sigma > 0 — refused.

    Draws clamp to [1, size_cap_factor * nbytes] so one astronomical
    tail sample cannot unbound a sweep point's runtime; the clamp is
    part of the documented law (docs/SWEEP.md)."""
    import math

    from shadow_tpu.core.rng import (STREAM_RPC_SIZE, mix_key,
                                     threefry2x32_py)
    if size_law is None:
        return [[nbytes] * bursts for _ in range(n_clients)]
    if size_law not in ("pareto", "lognormal"):
        raise ValueError(f"unknown size_law {size_law!r}; expected "
                         f"'pareto' or 'lognormal' (or None for "
                         f"fixed sizes)")
    if size_law == "pareto" and not size_shape > 1.0:
        raise ValueError(f"pareto size_shape must be > 1 (finite "
                         f"mean), got {size_shape}")
    if size_law == "lognormal" and not size_sigma > 0.0:
        raise ValueError(f"lognormal size_sigma must be > 0, "
                         f"got {size_sigma}")
    k0, k1 = mix_key(seed, STREAM_RPC_SIZE)
    cap = max(size_cap_factor * nbytes, 1)

    def u01(c0: int, c1: int) -> float:
        b0, b1 = threefry2x32_py(k0, k1, c0 & 0xFFFFFFFF,
                                 c1 & 0xFFFFFFFF)
        # top 53 bits -> (0, 1]: never exactly 0, so logs/powers are
        # finite
        return ((((b1 << 32) | b0) >> 11) + 1) * (2.0 ** -53)

    out: list[list[int]] = []
    for c in range(n_clients):
        row = []
        for b in range(bursts):
            if size_law == "pareto":
                # mean = alpha * xm / (alpha - 1) == nbytes
                xm = nbytes * (size_shape - 1.0) / size_shape
                size = xm * u01(c, b) ** (-1.0 / size_shape)
            else:
                # mean = exp(mu + sigma^2/2) == nbytes; Box-Muller on
                # two independent counters (burst index split even/odd
                # keeps the pair disjoint from other draws)
                u1 = u01(c, 2 * bursts + 2 * b)
                u2 = u01(c, 2 * bursts + 2 * b + 1)
                z = math.sqrt(-2.0 * math.log(u1)) \
                    * math.cos(2.0 * math.pi * u2)
                mu = math.log(nbytes) - size_sigma * size_sigma / 2.0
                size = math.exp(mu + size_sigma * z)
            row.append(max(1, min(int(size), cap)))
        out.append(row)
    return out


def rpc_burst_yaml(n_clients: int = 8, n_servers: int = 2,
                   nbytes: int = 20_000, bursts: int = 4,
                   burst_interval_ms: int = 250, count: int = 4,
                   server_bw: str = "50 Mbit",
                   client_bw: str = "100 Mbit",
                   latency: str = "1 ms", stop_time: str = "3s",
                   seed: int = 31, scheduler: str = "serial",
                   device_spans: str | None = None,
                   tcp: dict | None = None,
                   size_law: str | None = None,
                   size_shape: float = 1.5,
                   size_sigma: float = 1.0) -> str:
    """Open-loop bursty request/response traffic: every client host
    runs one tgen-client PROCESS PER BURST — process b starts at the
    b-th burst instant regardless of whether earlier transfers
    finished (that is what makes the load open-loop rather than a
    closed request loop), and each process issues `count` short
    `nbytes` responses back-to-back.  Whole bursts land on the
    servers' downlinks at the same instant, so the per-burst queue
    excursions — and, under tcp={"cc": "dctcp", "ecn": "on"}, the
    CE-mark episodes — are sharply separated in the fabric channel.

    `size_law` switches the per-burst response size from fixed
    `nbytes` to the heavy-tailed laws of arXiv 2205.01234 (see
    rpc_sizes: "pareto" / "lognormal", mean preserved at `nbytes`,
    threefry-deterministic per (client, burst))."""
    sizes = rpc_sizes(seed, n_clients, bursts, nbytes, size_law,
                      size_shape, size_sigma)
    gml_lines = ["graph [ directed 0",
                 f'  node [ id 0 host_bandwidth_down "{server_bw}" '
                 f'host_bandwidth_up "{server_bw}" ]',
                 f'  node [ id 1 host_bandwidth_down "{client_bw}" '
                 f'host_bandwidth_up "{client_bw}" ]',
                 f'  edge [ source 0 target 0 latency "{latency}" ]',
                 f'  edge [ source 1 target 1 latency "{latency}" ]',
                 f'  edge [ source 0 target 1 latency "{latency}" ]',
                 "]"]
    gml = "\n".join(gml_lines)
    tl = _tcp_line(tcp)
    blocks = []
    for s in range(n_servers):
        blocks.append(
            f"  rpcsrv{s:02d}:\n    network_node_id: 0\n"
            f"{tl}    processes:\n"
            f'      - {{ path: tgen-server, args: ["8080"], '
            f"expected_final_state: running }}")
    for c in range(n_clients):
        server = f"rpcsrv{c % n_servers:02d}"
        procs = []
        for b in range(bursts):
            # sub-ms stagger inside a burst keeps ISS draws ordered
            # but the burst's flows land within one RTT of each other
            start_ms = 100 + b * burst_interval_ms
            start_us = (c * 73) % 500
            procs.append(
                f'      - {{ path: tgen-client, '
                f'args: [{server}, "8080", "{sizes[c][b]}", '
                f'"{count}"], '
                f"start_time: {start_ms * 1000 + start_us} us, "
                f"expected_final_state: any }}")
        blocks.append(
            f"  rpccli{c:03d}:\n    network_node_id: 1\n"
            f"{tl}    processes:\n" + "\n".join(procs))
    exp = [f"  scheduler: {scheduler}",
           "  socket_send_autotune: false",
           "  socket_recv_autotune: false"]
    if device_spans is not None:
        exp.append(f"  tpu_device_spans: {device_spans}")
    return (f"general: {{ stop_time: {stop_time}, seed: {seed} }}\n"
            f"network:\n  graph:\n    type: gml\n    inline: |\n"
            f"{_indent(gml, '      ')}\n"
            f"experimental:\n" + "\n".join(exp) + "\n"
            f"hosts:\n" + "\n".join(blocks) + "\n")
