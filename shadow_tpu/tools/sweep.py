"""Sweep-campaign CLI (docs/SWEEP.md).

    python -m shadow_tpu.tools.sweep expand SPEC.yaml
    python -m shadow_tpu.tools.sweep run    SPEC.yaml --out DIR
    python -m shadow_tpu.tools.sweep report DATASET.swds
    python -m shadow_tpu.tools.sweep --smoke

`expand` prints the deterministic run matrix without executing;
`run` executes every point in identity-safe subprocesses (warm-
starting fork groups when the spec asks), aggregates the channels
into `DIR/<name>.swds`, and prints the tail-curve tables; `report`
re-renders a dataset's curves and verdicts.  `--smoke` (the
./setup sweep target) runs a 2-point micro-campaign TWICE into
temporary directories, byte-compares the two datasets, checks the
aggregator's conservation verdict, and exits nonzero on any
difference — the zero-cost standing proof that campaign bytes depend
only on the spec.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_spec(path: str) -> dict:
    import yaml
    with open(path) as f:
        return yaml.safe_load(f)


def print_curves(meta: dict, out=None) -> None:
    out = out or sys.stdout
    print(f"campaign {meta['name']}: {len(meta['points'])} points",
          file=out)
    for curve in meta["tail_curves"]:
        key = {k: v for k, v in curve["key"].items()
               if v not in (0, "fixed") or k == "cc"}
        print(f"  curve {json.dumps(key, sort_keys=True)} "
              f"(p99 monotone {curve['p99_monotone_frac']:.0%}):",
              file=out)
        print(f"    {'load':>6} {'flows':>6} {'p50 ms':>9} "
              f"{'p99 ms':>9} {'p999 ms':>9}", file=out)
        for r in curve["rows"]:
            print(f"    {r['load']:>6} {r['flows']:>6} "
                  f"{r['p50_ns'] / 1e6:>9.2f} "
                  f"{r['p99_ns'] / 1e6:>9.2f} "
                  f"{r['p999_ns'] / 1e6:>9.2f}", file=out)


def cmd_expand(spec_path: str) -> int:
    from shadow_tpu.sweep import spec as spec_mod
    spec = spec_mod.validate_spec(_load_spec(spec_path))
    points = spec_mod.expand(spec)
    print(f"{spec['name']}: {len(points)} point(s), scenario "
          f"{spec['scenario']}, seeds {spec['seeds']}")
    for p in points:
        print(f"  {p['point_id']}  group={p['group']}")
    return 0


def cmd_run(spec_path: str, out_dir: str, resume: bool = False) -> int:
    from shadow_tpu.sweep import dataset, runner
    from shadow_tpu.sweep import spec as spec_mod
    spec = spec_mod.validate_spec(_load_spec(spec_path))
    runner.run_campaign(spec, out_dir, resume=resume)
    ds = dataset.aggregate(spec, out_dir)
    path = os.path.join(out_dir, f"{spec['name']}.swds")
    ds.write(path)
    print(f"dataset: {path} ({os.path.getsize(path)} bytes)")
    print_curves(ds.meta)
    for fp in ds.meta.get("failed_points", []):
        print(f"  FAILED point {fp['point_id']}: "
              f"{fp['error'].splitlines()[0] if fp['error'] else '?'}")
    return 0


def cmd_report(path: str) -> int:
    from shadow_tpu.sweep import dataset
    ds = dataset.load(path)
    print_curves(ds.meta)
    warm = sum(1 for p in ds.meta["points"] if p["warm_started"])
    print(f"  flows {sum(p['counts']['flows'] for p in ds.meta['points'])}, "
          f"link samples "
          f"{sum(p['counts']['links'] for p in ds.meta['points'])}, "
          f"warm-started points {warm}")
    failed = ds.meta.get("failed_points", [])
    if failed:
        print(f"  FAILED points ({len(failed)} — recorded honestly, "
              f"docs/ROBUSTNESS.md):")
        for fp in failed:
            first = fp["error"].splitlines()[0] if fp["error"] else "?"
            print(f"    {fp['point_id']}: {first}")
    return 0


SMOKE_SPEC = {
    "name": "smoke", "scenario": "incast",
    "base": {"nbytes": 40_000, "stop_time": "800ms", "fan_in": 2},
    "axes": {"fan_in": [2, 3]},
    "time_limit_s": 240,
}


def smoke() -> int:
    """2-point micro-campaign run twice -> byte-identical datasets +
    aggregator conservation verdict (the ./setup sweep target)."""
    import tempfile

    from shadow_tpu.sweep import dataset, runner
    blobs = []
    with tempfile.TemporaryDirectory() as td:
        for tag in ("a", "b"):
            out = os.path.join(td, tag)
            runner.run_campaign(SMOKE_SPEC, out,
                                log=lambda m: None)
            ds = dataset.aggregate(SMOKE_SPEC, out)
            blobs.append(ds.to_bytes())
    if blobs[0] != blobs[1]:
        print("sweep smoke: two identical campaigns produced "
              "DIFFERENT dataset bytes", file=sys.stderr)
        return 1
    flows = sum(p["counts"]["flows"] for p in ds.meta["points"])
    print(f"sweep smoke: ok (2-point campaign byte-identical across "
          f"two runs, {len(blobs[0])} dataset bytes, {flows} flows, "
          f"conservation ok)")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] in ("expand", "run", "report"):
        sub = argparse.ArgumentParser(
            prog=f"shadow_tpu.tools.sweep {argv[0]}")
        if argv[0] == "report":
            sub.add_argument("dataset")
        else:
            sub.add_argument("spec")
        if argv[0] == "run":
            sub.add_argument("--out", required=True)
            sub.add_argument(
                "--resume", action="store_true",
                help="skip points whose completion marker exists "
                     "(re-run only missing/failed points)")
        sargs = sub.parse_args(argv[1:])
        from shadow_tpu.sweep.dataset import DatasetError
        from shadow_tpu.sweep.runner import PointFailure
        from shadow_tpu.sweep.spec import SpecError
        try:
            if argv[0] == "expand":
                return cmd_expand(sargs.spec)
            if argv[0] == "run":
                return cmd_run(sargs.spec, sargs.out,
                               resume=sargs.resume)
            return cmd_report(sargs.dataset)
        except (SpecError, PointFailure, DatasetError) as e:
            print(f"sweep: {e}", file=sys.stderr)
            return 1
    ap = argparse.ArgumentParser(prog="shadow_tpu.tools.sweep",
                                 description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="2-point micro-campaign byte-identity + "
                         "conservation smoke")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke()
    ap.print_usage(sys.stderr)
    print("sweep: a subcommand (expand/run/report) or --smoke is "
          "required", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
