"""Twin-contract, determinism & effects lint CLI.

    python -m shadow_tpu.tools.lint [--pass twin,layout,det,effects]
                                    [--json]

Runs the shadow_tpu/analysis/ passes (docs/LINT.md) and exits non-zero
on any violation.  Pure parsing — no JAX, no engine import — so it is
cheap enough to gate every test run and benchmark recording.

`--pass` also accepts the pass numbers (`--pass 4`, `--pass 1,3`):
1 = twin, 2 = layout, 3 = det, 4 = effects.

Exit-code contract (CI and bench's preflight key on it):
    0  every requested pass ran clean
    1  at least one violation (all reported, on stdout or in --json)
    2  usage error (unknown pass name/number); nothing was linted
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

PASSES = ("twin", "layout", "det", "effects")

# numeric aliases: the docs and the ISSUE tracker talk about the
# passes by number, so `--pass 4` must mean the effects pass
_NUMERIC = {str(i + 1): name for i, name in enumerate(PASSES)}


def repo_root() -> str:
    """shadow_tpu/tools/lint.py -> the repo checkout root."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run(passes=PASSES, root: str | None = None):
    from shadow_tpu.analysis import run_all

    return run_all(root or repo_root(), passes=passes)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shadow_tpu.tools.lint", description=__doc__)
    ap.add_argument("--pass", dest="passes", default=",".join(PASSES),
                    help="comma-separated subset of: twin,layout,det,"
                         "effects (or numbers 1-4)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    passes = tuple(_NUMERIC.get(p.strip(), p.strip())
                   for p in args.passes.split(",") if p.strip())
    bad = [p for p in passes if p not in PASSES]
    if bad:
        print(f"unknown pass(es): {', '.join(bad)}", file=sys.stderr)
        return 2

    t0 = time.perf_counter()  # shadow-lint: allow[wall-clock] CLI timing
    violations, counts = run(passes)
    dt = time.perf_counter() - t0  # shadow-lint: allow[wall-clock] CLI timing

    if args.json:
        print(json.dumps({
            "violations": [vars(v) for v in violations],
            "counts": counts,
            "seconds": round(dt, 3),
        }))
    else:
        from shadow_tpu.analysis import format_report
        print(format_report(violations, counts))
        print(f"({', '.join(passes)} in {dt:.2f}s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
