"""Checkpoint-archive CLI (docs/CHECKPOINT.md).

    python -m shadow_tpu.tools.ckpt info   SNAPSHOT
    python -m shadow_tpu.tools.ckpt verify SNAPSHOT
    python -m shadow_tpu.tools.ckpt diff   SNAPSHOT_A SNAPSHOT_B
    python -m shadow_tpu.tools.ckpt fork   SNAPSHOT BASE.yaml \
        VARIANT.yaml [VARIANT2.yaml ...] [--out-dir DIR]
    python -m shadow_tpu.tools.ckpt --smoke [--hosts N]

`info` prints the snapshot's round/sim-time/host-count plus the
section table (sizes + checksums); `verify` re-checksums every section
and gates on the layout version; `diff` compares two snapshots section
by section and names the first differing section — drilling into the
engine plane blob to name the first differing HOST frame.  `fork`
clones one post-ramp snapshot into N config-variant resume points
(ckpt/fork.py: variants may differ only in the fork-safe knobs —
swept DCTCP-K, stop_time — with a clear refusal otherwise; the warm-
start seam the sweep runner uses, docs/SWEEP.md).  `--smoke`
(the ./setup ckpt target) runs a 50-host tgen sim, snapshots it
mid-run, resumes, and byte-compares every determinism-gated artifact
of the resumed run against the straight run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import zlib

from shadow_tpu.ckpt import format as ck


def info(path: str) -> int:
    meta = ck.read_meta(path)
    table = ck.section_table(path)
    print(f"{path}:")
    print(f"  layout version : {ck.CK_VERSION}")
    print(f"  round          : {meta['rounds']} "
          f"(span rounds {meta['span_rounds']})")
    print(f"  sim time       : {meta['next_start_ns'] / 1e9:.6f} s "
          f"(busy end {meta['busy_end_ns'] / 1e9:.6f} s)")
    print(f"  hosts          : {meta['n_hosts']} "
          f"({'engine' if meta['engine'] else 'object'} path)")
    print(f"  seed           : {meta['seed']}")
    print(f"  runahead       : {meta['runahead_ns']} ns")
    print(f"  faults applied : {meta['faults_applied']}")
    if meta.get("managed"):
        print(f"  managed        : {meta['managed']} restart "
              f"record(s) — resume restarts these binaries fresh "
              f"under final-state gating")
    print(f"  config digest  : {meta['config_digest'][:16]}…")
    print("  sections:")
    for sid, crc, length in table:
        name = ck.CK_SEC_NAMES.get(sid, f"#{sid}")
        print(f"    {name:<8} {length:>12} B  crc32 {crc:08x}")
    sections = ck.read_archive(path)
    if ck.CK_SEC_PLANE in sections:
        _epoch, frames = ck.parse_plane_frames(
            sections[ck.CK_SEC_PLANE])
        n_hosts = sum(1 for fid in frames if fid != ck.CK_GLOBAL_FRAME)
        print(f"  engine plane   : {n_hosts} host frame(s)")
    return 0


def verify(path: str) -> int:
    table = ck.section_table(path)  # magic + layout-version gate
    bad = 0
    off = ck.CK_HDR_BYTES + ck.CK_SEC_HDR_BYTES * len(table)
    with open(path, "rb") as f:
        f.seek(off)
        for sid, crc, length in table:
            payload = f.read(length)
            name = ck.CK_SEC_NAMES.get(sid, f"#{sid}")
            if len(payload) != length:
                print(f"  {name}: TRUNCATED ({len(payload)}/{length} B)")
                bad += 1
                continue
            actual = zlib.crc32(payload) & 0xFFFFFFFF
            if actual != crc:
                print(f"  {name}: CHECKSUM MISMATCH "
                      f"({actual:08x} != {crc:08x})")
                bad += 1
            else:
                print(f"  {name}: ok ({length} B)")
    # The plane blob carries its own (engine-build) layout version.
    if not bad:
        sections = ck.read_archive(path)
        if ck.CK_SEC_PLANE in sections:
            try:
                ck.parse_plane_frames(sections[ck.CK_SEC_PLANE])
            except ck.CkptError as e:
                print(f"  plane: {e}")
                bad += 1
    print("verify:", "FAIL" if bad else "ok")
    return 1 if bad else 0


def diff(path_a: str, path_b: str) -> int:
    sa = ck.read_archive(path_a)
    sb = ck.read_archive(path_b)
    first = None
    for sid in sorted(set(sa) | set(sb)):
        name = ck.CK_SEC_NAMES.get(sid, f"#{sid}")
        a, b = sa.get(sid), sb.get(sid)
        if a == b:
            print(f"  {name}: identical "
                  f"({len(a) if a is not None else 0} B)")
            continue
        if a is None or b is None:
            print(f"  {name}: only in "
                  f"{path_a if b is None else path_b}")
        elif sid == ck.CK_SEC_PLANE:
            ea, fa = ck.parse_plane_frames(a)
            eb, fb = ck.parse_plane_frames(b)
            hosts = sorted(
                fid for fid in set(fa) | set(fb)
                if fa.get(fid) != fb.get(fid))
            named = ["global" if h == ck.CK_GLOBAL_FRAME else f"host {h}"
                     for h in hosts[:8]]
            extra = f" (+{len(hosts) - 8} more)" if len(hosts) > 8 else ""
            print(f"  {name}: DIFFERS — first differing frame(s): "
                  f"{', '.join(named)}{extra}"
                  + (f"; state epoch {ea} vs {eb}" if ea != eb else ""))
        elif sid == ck.CK_SEC_META:
            ma, mb = json.loads(a.decode()), json.loads(b.decode())
            keys = sorted(k for k in set(ma) | set(mb)
                          if ma.get(k) != mb.get(k))
            print(f"  {name}: DIFFERS — keys: {', '.join(keys)}")
        else:
            n = next((i for i, (x, y) in enumerate(zip(a, b))
                      if x != y), min(len(a), len(b)))
            print(f"  {name}: DIFFERS ({len(a)} vs {len(b)} B, "
                  f"first difference at byte {n})")
        if first is None:
            first = name
    if first is None:
        print("diff: identical")
        return 0
    print(f"diff: first differing section: {first}")
    return 1


def fork(snapshot: str, base_yaml: str, variant_yamls: list[str],
         out_dir: str) -> int:
    """`ckpt fork`: one forked archive per variant config, named
    <variant stem>.stck in `out_dir`."""
    from shadow_tpu.ckpt.fork import fork_archive
    from shadow_tpu.core.config import ConfigOptions

    base = ConfigOptions.from_file(base_yaml)
    os.makedirs(out_dir, exist_ok=True)
    for vy in variant_yamls:
        variant = ConfigOptions.from_file(vy)
        stem = os.path.splitext(os.path.basename(vy))[0]
        out = os.path.join(out_dir, f"{stem}.stck")
        keys = fork_archive(snapshot, base, variant, out)
        print(f"forked {out}: "
              + (", ".join(keys) if keys else "identical config"))
    return 0


def _collect(dirpath: str) -> dict:
    """Determinism-gate artifact collection (tests/test_determinism.py
    collect() semantics: metrics.wall and the wall channel stripped,
    volatile processed-config lines normalized)."""
    import re
    out = {}
    for root, _, files in os.walk(dirpath):
        for fn in files:
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, dirpath)
            with open(p, "rb") as f:
                data = f.read()
            if fn == "sim-stats.json":
                stats = json.loads(data)
                stats.get("metrics", {}).pop("wall", None)
                data = json.dumps(stats, indent=2,
                                  sort_keys=True).encode()
            if fn == "flight-wall.json":
                data = b"<wall>"
            if fn == "processed-config.yaml":
                data = re.sub(rb"data_directory: .*", b"<n>", data)
                data = re.sub(rb"directory: .*", b"<n>", data)
            out[rel] = data
    return out


def smoke(n_hosts: int) -> int:
    """50-host run -> snapshot -> resume -> byte-compare (the
    ./setup ckpt target): every determinism-gated artifact of the
    resumed run must equal the straight run's."""
    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import resume_simulation, run_simulation
    from shadow_tpu.tools.netgen import tcp_stream_yaml

    with tempfile.TemporaryDirectory() as td:
        text = tcp_stream_yaml(n_hosts, loss=0.005, stop_time="2s",
                               seed=11, scheduler="tpu")

        def cfg(sub, snapdir):
            config = ConfigOptions.from_yaml_text(text)
            config.general.data_directory = os.path.join(td, sub)
            config.experimental.sim_netstat = "on"
            config.experimental.sim_fabricstat = "on"
            from shadow_tpu.core.config import CheckpointConfig
            config.checkpoint = CheckpointConfig(
                at_ns=[1_000_000_000],
                directory=os.path.join(td, snapdir))
            return config

        _m, s = run_simulation(cfg("straight", "snaps"),
                               write_data=True)
        if not s.ok:
            print(f"ckpt smoke: sim failed: {s.plugin_errors[:3]}",
                  file=sys.stderr)
            return 1
        snap = os.path.join(td, "snaps", "ckpt-1000000000.stck")
        if not os.path.exists(snap):
            print("ckpt smoke: no snapshot written", file=sys.stderr)
            return 1
        if info(snap) != 0 or verify(snap) != 0:
            return 1
        _m2, s2 = resume_simulation(cfg("resumed", "snaps2"), snap,
                                    write_data=True)
        if not s2.ok:
            print(f"ckpt smoke: resume failed: {s2.plugin_errors[:3]}",
                  file=sys.stderr)
            return 1
        a = _collect(os.path.join(td, "straight"))
        b = _collect(os.path.join(td, "resumed"))
        bad = [rel for rel in sorted(set(a) | set(b))
               if a.get(rel) != b.get(rel)]
        if bad:
            print(f"ckpt smoke: resumed artifacts diverged: {bad}",
                  file=sys.stderr)
            return 1
        # The resumed snapshot schedule was already consumed: the
        # second run writes none (documented: times <= the resume
        # point are skipped).
    print(f"ckpt smoke: ok ({n_hosts} hosts, snapshot at round "
          f"boundary >= 1s, resume byte-identical across "
          f"{len(a)} artifacts)")
    return 0


def smoke_managed(n_procs: int) -> int:
    """Managed-fleet restart smoke (the ./setup managed target):
    `n_procs` REAL binaries under the shim -> snapshot mid-activity ->
    restart-resume -> final-state gate (docs/CHECKPOINT.md "Managed
    processes").  The resumed run carries no byte-continuation
    contract (the binaries re-run), but two resumes of the same
    archive must agree byte-for-byte — both are asserted here."""
    import shutil
    import tempfile

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import resume_simulation, run_simulation

    if shutil.which("cc") is None:
        print("managed smoke: skipped (no C toolchain for the shim)",
              file=sys.stderr)
        return 0
    with tempfile.TemporaryDirectory() as td:
        # Shared fleet generator + binary builder (bench's
        # managed-1k/10k rungs use them too): per-server echo budgets
        # and explicit server IPs stay correct at ANY n_procs.
        from shadow_tpu.core.config import CheckpointConfig
        from shadow_tpu.tools.netgen import (compile_echo_binaries,
                                             managed_fleet_yaml)
        bins = compile_echo_binaries(td)
        text = managed_fleet_yaml(bins["udp_echo_server"],
                                  bins["udp_echo_client"], n_procs,
                                  stop_time="20s", seed=7)

        def cfg(sub):
            config = ConfigOptions.from_yaml_text(text)
            config.general.data_directory = os.path.join(td, sub)
            # Boundary mid-activity: clients start at 2s, pings take
            # ~20 ms RTT each, so 2030 ms lands inside the exchange.
            config.checkpoint = CheckpointConfig(
                at_ns=[2_030_000_000],
                directory=os.path.join(td, "snaps"))
            return config

        m, s = run_simulation(cfg("straight"))
        snap = getattr(m, "ckpt_last_path", None)
        if not s.ok or snap is None:
            print(f"managed smoke: straight run failed "
                  f"(ok={s.ok}, snapshot={snap}, "
                  f"{s.plugin_errors[:3]})", file=sys.stderr)
            return 1
        if info(snap) != 0 or verify(snap) != 0:
            return 1
        m2, s2 = resume_simulation(cfg("resumed"), snap)
        if not s2.ok:
            print(f"managed smoke: restart-resume failed the final-"
                  f"state gate: {s2.plugin_errors[:3]}",
                  file=sys.stderr)
            return 1
        m3, s3 = resume_simulation(cfg("resumed2"), snap)
        if not s3.ok or m2.trace_lines() != m3.trace_lines():
            print("managed smoke: two resumes of the same archive "
                  "diverged", file=sys.stderr)
            return 1
        restarted = sum(
            1 for h in m2.hosts for p in h.processes.values()
            if p.exited and p.exit_code == 0)
    print(f"managed smoke: ok ({n_procs} real binaries, snapshot "
          f"mid-activity, restart-resume passed the final-state gate "
          f"with {restarted} clean exits, resume-vs-resume "
          f"byte-identical)")
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] in ("info", "verify", "diff", "fork"):
        sub = argparse.ArgumentParser(
            prog=f"shadow_tpu.tools.ckpt {argv[0]}")
        sub.add_argument("snapshot")
        if argv[0] == "diff":
            sub.add_argument("snapshot_b")
        if argv[0] == "fork":
            sub.add_argument("base_yaml")
            sub.add_argument("variant_yamls", nargs="+")
            sub.add_argument("--out-dir", default=".")
        sargs = sub.parse_args(argv[1:])
        try:
            if argv[0] == "info":
                return info(sargs.snapshot)
            if argv[0] == "verify":
                return verify(sargs.snapshot)
            if argv[0] == "fork":
                return fork(sargs.snapshot, sargs.base_yaml,
                            sargs.variant_yamls, sargs.out_dir)
            return diff(sargs.snapshot, sargs.snapshot_b)
        except ck.CkptError as e:
            print(f"ckpt: {e}", file=sys.stderr)
            return 1
    ap = argparse.ArgumentParser(prog="shadow_tpu.tools.ckpt",
                                 description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="run the 50-host snapshot/resume smoke and "
                         "exit nonzero unless artifacts byte-match")
    ap.add_argument("--hosts", type=int, default=50,
                    help="host count for --smoke (default 50)")
    ap.add_argument("--smoke-managed", type=int, metavar="N",
                    help="run the managed-fleet restart smoke with N "
                         "real binaries (the ./setup managed target)")
    args = ap.parse_args(argv)
    if args.smoke_managed:
        from shadow_tpu.utils.platform import honor_platform_env
        honor_platform_env()
        return smoke_managed(args.smoke_managed)
    if args.smoke:
        from shadow_tpu.utils.platform import honor_platform_env
        honor_platform_env()
        return smoke(args.hosts)
    ap.print_usage(sys.stderr)
    print("ckpt: a subcommand (info/verify/diff) or --smoke is "
          "required", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
