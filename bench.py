#!/usr/bin/env python
"""Benchmark: 100-host UDP mesh (BASELINE.md config 2), end-to-end.

Runs the same workload under the reference-style thread-per-core
scheduler (baseline) and the batched `--scheduler=tpu` backend, and
prints ONE JSON line:

    {"metric": ..., "value": <tpu packet-events/sec>, "unit": ...,
     "vs_baseline": <tpu rate / thread_per_core rate>}

The TPU run is executed twice and the second (warm, jit-cached) run is
measured. If no accelerator platform initializes within the watchdog
window (the tunnel can be down in CI), the kernel runs on the CPU
backend — same code path, still a valid scheduler-vs-scheduler ratio.
"""

import json
import multiprocessing
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

HOSTS = 100
COUNT = 30          # datagrams per peer per host
SIZE = 200
LOSS = 0.01         # forces the loss-RNG path on every data packet


def _probe_tpu(queue):
    try:
        import jax
        devs = jax.devices()
        queue.put(str(devs[0].platform))
    except Exception as e:  # pragma: no cover
        queue.put(f"error: {e}")


def tpu_available(timeout_s: float = 45.0) -> bool:
    """The site TPU plugin dials a tunnel that can hang; probe it in a
    subprocess so a dead tunnel degrades to CPU instead of hanging."""
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_probe_tpu, args=(q,))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.terminate()
        p.join()
        return False
    try:
        result = q.get_nowait()
    except Exception:
        return False
    return not result.startswith("error") and result != "cpu"


def build_config(scheduler: str):
    from shadow_tpu.core.config import ConfigOptions

    names = [f"h{i:03d}" for i in range(HOSTS)]
    hosts = {}
    for name in names:
        peers = [p for p in names if p != name]
        hosts[name] = {
            "network_node_id": 0,
            "processes": [{
                "path": "udp-mesh",
                "args": ["9000", str(COUNT), str(SIZE)] + peers,
                "start_time": "1s",
                "expected_final_state": "any",
            }],
        }
    return ConfigOptions.from_dict({
        "general": {"stop_time": "30s", "seed": 3},
        "network": {"graph": {"type": "gml", "inline": f"""
graph [ node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss {LOSS} ] ]"""}},
        "experimental": {"scheduler": scheduler},
        "hosts": hosts})


def run_once(scheduler: str):
    from shadow_tpu.core.manager import Manager

    manager = Manager(build_config(scheduler))
    for h in manager.hosts:
        h.tracing_enabled = False
    t0 = time.perf_counter()
    summary = manager.run()
    wall = time.perf_counter() - t0
    return summary, wall


def main() -> None:
    if not tpu_available():
        from shadow_tpu.utils.platform import force_cpu
        force_cpu()
        print("bench: accelerator unavailable; kernel on CPU backend",
              file=sys.stderr)

    # Baseline: the reference's scheduler design.
    base_summary, base_wall = run_once("thread_per_core")
    base_rate = base_summary.packets_sent / base_wall

    # TPU scheduler: warmup (compiles the batch buckets), then measure.
    run_once("tpu")
    tpu_summary, tpu_wall = run_once("tpu")
    tpu_rate = tpu_summary.packets_sent / tpu_wall

    assert tpu_summary.packets_sent == base_summary.packets_sent, \
        "schedulers disagreed on workload size"

    print(json.dumps({
        "metric": f"packet-events/sec, {HOSTS}-host udp mesh "
                  f"(scheduler=tpu vs thread_per_core)",
        "value": round(tpu_rate, 1),
        "unit": "packets/sec",
        "vs_baseline": round(tpu_rate / base_rate, 3),
    }))


if __name__ == "__main__":
    main()
