#!/usr/bin/env python
"""Benchmark: the BASELINE.md scale ladder, headline = 10k-host tgen TCP.

Runs the same workloads under the reference-style thread-per-core
scheduler (baseline) and the batched `--scheduler=tpu` backend, and
prints ONE JSON line:

    {"metric": ..., "value": <tpu sim-seconds/wallclock-sec>,
     "unit": ..., "vs_baseline": <tpu rate / thread_per_core rate>}

Headline (BASELINE config 4 shape): a 10,000-host Tor-class config —
500 relay-tier servers on the core serve repeated 25 KB transfers to
9,500 clients behind lossy mid/leaf tiers — exercising TCP
retransmission, CoDel, token buckets, and cross-host propagation for
the whole simulated window.  Secondary numbers on stderr: the 1k-host
3-tier config (round-2's headline) and the 100-host UDP mesh
(round-1's).  Both schedulers must agree on exact packet counts
(byte-identical traces are gated in tests/ at 1k and mesh scale).

The TPU run is executed twice and the second (warm, jit-cached) run is
measured. If no accelerator platform initializes within the watchdog
window (the tunnel can be down in CI), the kernel runs on the CPU
backend — same code path, still a valid scheduler-vs-scheduler ratio.
"""

import json
import multiprocessing
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

HOSTS_10K = 10_000
SIM_SECONDS_10K = 10

HOSTS = 1000
SERVERS = HOSTS // 10
NBYTES = 50_000
COUNT = 5           # transfers per client
SIM_SECONDS = 30

MESH_HOSTS = 100
MESH_COUNT = 30
MESH_SIZE = 200

THREE_TIER_GML = """
graph [ directed 0
  node [ id 0 host_bandwidth_down "10 Gbit" host_bandwidth_up "10 Gbit" ]
  node [ id 1 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  node [ id 2 host_bandwidth_down "100 Mbit" host_bandwidth_up "50 Mbit" ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 0 target 1 latency "10 ms" packet_loss 0.002 ]
  edge [ source 1 target 1 latency "5 ms" packet_loss 0.001 ]
  edge [ source 1 target 2 latency "25 ms" packet_loss 0.005 ]
  edge [ source 2 target 2 latency "40 ms" packet_loss 0.01 ]
  edge [ source 0 target 2 latency "35 ms" packet_loss 0.008 ]
]"""


def _probe_tpu(queue):
    try:
        import jax
        devs = jax.devices()
        queue.put(str(devs[0].platform))
    except Exception as e:  # pragma: no cover
        queue.put(f"error: {e}")


def tpu_available(timeout_s: float = 45.0) -> bool:
    """The site TPU plugin dials a tunnel that can hang; probe it in a
    subprocess so a dead tunnel degrades to CPU instead of hanging."""
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_probe_tpu, args=(q,))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.terminate()
        p.join()
        return False
    try:
        result = q.get_nowait()
    except Exception:
        return False
    return not result.startswith("error") and result != "cpu"


def config3(scheduler: str):
    """BASELINE config 3: 1k hosts over the 3-tier latency/loss graph,
    tgen-style repeated TCP transfers."""
    from shadow_tpu.core.config import ConfigOptions

    hosts = {}
    for i in range(SERVERS):
        hosts[f"srv{i:03d}"] = {
            "network_node_id": 0,
            "processes": [{
                "path": "tgen-server", "args": ["80"],
                "expected_final_state": "running",
            }],
        }
    for i in range(HOSTS - SERVERS):
        hosts[f"cli{i:04d}"] = {
            "network_node_id": 1 + (i % 2),
            "processes": [{
                "path": "tgen-client",
                "args": [f"srv{i % SERVERS:03d}", "80", str(NBYTES),
                         str(COUNT)],
                "start_time": f"{100 + (i % 20) * 37}ms",
                "expected_final_state": "any",
            }],
        }
    return ConfigOptions.from_dict({
        "general": {"stop_time": f"{SIM_SECONDS}s", "seed": 7},
        "network": {"graph": {"type": "gml", "inline": THREE_TIER_GML}},
        "experimental": {"scheduler": scheduler},
        "hosts": hosts})


def config_10k(scheduler: str, stop_s: int = SIM_SECONDS_10K,
               extra_hosts: dict | None = None, data_dir: str | None = None,
               **exp_extra):
    """BASELINE config 4 shape: 10k hosts, tornettools-ish tiers (5%
    relay servers on the core, clients behind lossy mid/leaf edges)."""
    from shadow_tpu.core.config import ConfigOptions

    relays = HOSTS_10K // 20
    hosts = {}
    for i in range(relays):
        hosts[f"relay{i:04d}"] = {
            "network_node_id": 0,
            "processes": [{
                "path": "tgen-server", "args": ["80"],
                "expected_final_state": "running",
            }],
        }
    for i in range(HOSTS_10K - relays):
        hosts[f"cli{i:05d}"] = {
            "network_node_id": 1 + (i % 2),
            "processes": [{
                "path": "tgen-client",
                "args": [f"relay{i % relays:04d}", "80", "25000", "3"],
                "start_time": f"{100 + (i % 50) * 17}ms",
                "expected_final_state": "any",
            }],
        }
    exp = {"scheduler": scheduler}
    exp.update(exp_extra)
    if extra_hosts:
        hosts.update(extra_hosts)
    general = {"stop_time": f"{stop_s}s", "seed": 7}
    if data_dir is not None:
        general["data_directory"] = data_dir
    return ConfigOptions.from_dict({
        "general": general,
        "network": {"graph": {"type": "gml", "inline": THREE_TIER_GML}},
        "experimental": exp,
        "hosts": hosts})


def mesh_config(scheduler: str):
    """Round-1 secondary: 100-host UDP mesh (BASELINE config 2)."""
    from shadow_tpu.core.config import ConfigOptions

    names = [f"h{i:03d}" for i in range(MESH_HOSTS)]
    hosts = {}
    for name in names:
        peers = [p for p in names if p != name]
        hosts[name] = {
            "network_node_id": 0,
            "processes": [{
                "path": "udp-mesh",
                "args": ["9000", str(MESH_COUNT), str(MESH_SIZE)] + peers,
                "start_time": "1s",
                "expected_final_state": "any",
            }],
        }
    return ConfigOptions.from_dict({
        "general": {"stop_time": "30s", "seed": 3},
        "network": {"graph": {"type": "gml", "inline": """
graph [ node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.01 ] ]"""}},
        "experimental": {"scheduler": scheduler},
        "hosts": hosts})


# Observations from the most recent run_once call: per-phase wall
# breakdown (flight recorder wall channel) + the device-eligibility
# histogram — recorded into the headline JSON and printed as one-line
# summaries (ISSUE 4 satellite).
LAST_RUN: dict = {}


def run_once(build, scheduler: str, report_routes: str | None = None,
             devcap: bool = False):
    from shadow_tpu.core.manager import Manager

    cfg = build(scheduler)
    # Wall-channel-only recording: phase walls per rung at a few
    # perf_counter reads per dispatch; the sim-time event stream stays
    # off so recorded rungs measure the simulator, not the recorder.
    cfg.experimental.flight_recorder = "wall"
    manager = Manager(cfg)
    for h in manager.hosts:
        h.set_tracing(False)
    if devcap and manager.plane is not None:
        # Opt-in per-round probe: how much of the run sat inside the
        # TCP device-span family's structural domain (ISSUE 1).  Off
        # by default — the scan costs ~1% at 10k hosts and must not
        # taint the other trials' walls.
        manager.plane.engine.set_devcap_probe(1)
    t0 = time.perf_counter()
    summary = manager.run()
    wall = time.perf_counter() - t0
    # Sim-netstat drop attribution + TCP stream totals (ISSUE 5): the
    # per-cause counters are always on, so every rung carries its
    # `drops` block without paying for the telemetry channel.
    net = manager.netstat_summary()
    tcp = net.get("tcp") or {}
    segs = tcp.get("segments_sent", 0)
    rtx_rate = (tcp.get("retransmits", 0) / segs) if segs else 0.0
    # Fabric observatory (ISSUE 8): the conservation counters are
    # always on, so every rung carries its `fabric` block (peak queue
    # depth, hottest-link utilization, FCT percentiles where TCP
    # flows exist) without paying for the sample channel.
    fabric = manager.fabric_summary(summary.busy_end_ns)
    LAST_RUN.clear()
    LAST_RUN.update({
        "scheduler": scheduler,
        "phases_s": manager.flight.wall.totals(),
        "eligibility": manager.audit.as_dict(),
        "drops": net["drops"],
        "retransmit_rate": round(rtx_rate, 6),
        "fabric": fabric,
    })
    prop = manager.propagator
    if getattr(prop, "n_shards", 1) > 1:
        # Sharded mesh backend (ISSUE 11): the per-round exchange's
        # packet split and wall (also credited to
        # metrics.wall.dispatch in sim-stats).
        LAST_RUN["exchange"] = {
            "packets_exchanged": prop.packets_exchanged,
            "packets_overflowed": prop.packets_overflowed,
            "exchange_wall_s": round(prop.exchange_wall_ns / 1e9, 3),
        }
    if report_routes is not None:
        print(f"bench[{report_routes}]: {route_split(manager)}",
              file=sys.stderr)
        drops_s = ", ".join(f"{k} {v}" for k, v in sorted(
            net["drops"].items(), key=lambda kv: -kv[1])) or "none"
        print(f"drops: {drops_s} | retransmit rate "
              f"{100.0 * rtx_rate:.3f}% "
              f"({tcp.get('retransmits', 0)}/{segs} segments)",
              file=sys.stderr)
        fct = fabric.get("fct", {})
        fct_s = (f" | fct p50 {fct['p50_ns'] / 1e6:.1f}ms p99 "
                 f"{fct['p99_ns'] / 1e6:.1f}ms p999 "
                 f"{fct['p999_ns'] / 1e6:.1f}ms ({fct['flows']} flows)"
                 if fct else "")
        print(f"fabric: peak queue {fabric['peak_queue_depth']}, "
              f"link util {100.0 * fabric['link_utilization']:.1f}%, "
              f"refill stalls {fabric['refill_stalls']}, "
              f"marks {fabric.get('marked_pkts', 0)}, "
              f"conservation {fabric['conservation']}{fct_s}",
              file=sys.stderr)
    if devcap and manager.plane is not None:
        rt, rf, steps, ok = manager.plane.engine.devcap_counters()
        frac = 100.0 * ok / steps if steps else 0.0
        print(f"bench[{report_routes or 'devcap'}]: TCP device-capable "
              f"rounds {rf}/{rt} fully, {frac:.1f}% of round-host "
              f"steps in-domain", file=sys.stderr)
    return summary, wall


def route_split(manager) -> str:
    """Device-vs-host dispatch split (VERDICT r3: make the accelerator
    claim auditable — how much propagation actually ran on the device
    vs the bit-identical host/C++ path)."""
    prop = manager.propagator
    rd = getattr(prop, "rounds_device", 0)
    pd = getattr(prop, "packets_device", 0)
    tot_r = getattr(prop, "rounds_dispatched", 0)
    tot_p = getattr(prop, "packets_batched", 0)
    return (f"dispatch split: {rd}/{tot_r} rounds on device, "
            f"{pd}/{tot_p} packets on device "
            f"({100.0 * pd / tot_p if tot_p else 0.0:.1f}%)")


def run_best(build, scheduler: str, trials: int = 2,
             report_routes: str | None = None):
    """Best-of-N wall time: machine noise (co-tenants, allocator state)
    swings single runs by 10-20%, which would dominate the recorded
    ratio.  The route split prints once (last trial).  The headline 10k
    comparison does NOT use this helper — it interleaves baseline and
    tpu trials itself so drift cannot favor a side."""
    best_summary, best_wall = None, None
    for i in range(trials):
        summary, wall = run_once(
            build, scheduler,
            report_routes=report_routes if i == trials - 1 else None)
        if best_wall is None or wall < best_wall:
            best_summary, best_wall = summary, wall
    return best_summary, best_wall


def _kern_rung_block(manager, runner):
    """Per-rung device-kernel attribution (ISSUE 15): the per-stage
    occupancy + attributed us/host/round table from the run's
    KernChannel, with the fires-vs-micro_iters conservation verdict.
    Returns (block dict, conserved bool) — a rung whose kernel
    channel fails conservation REFUSES to contribute to the
    crossover fit."""
    from shadow_tpu.trace.events import FAM_PHOLD
    from shadow_tpu.trace.kernstat import (DISPATCH_KEYS, attribution,
                                           check_conservation,
                                           family_totals,
                                           family_warm_wall_s)
    if manager.kern is None:
        return None, True
    ks = manager.kern.to_bytes()
    key = DISPATCH_KEYS[FAM_PHOLD]
    dispatch = {
        f"device_span_{key}": {
            "micro_iters": getattr(runner, "micro_iters", 0),
            "dispatch_wall_s": getattr(runner, "device_wall_ns", 0)
            / 1e9,
        },
        "fn_cache": {key: {
            "build_wall_s": getattr(runner, "fn_cache_build_ns", 0)
            / 1e9,
        }},
    }
    ok, problems = check_conservation(ks, dispatch,
                                      manager.kern.dropped)
    ent = family_totals(ks).get(FAM_PHOLD)
    if ent is None:
        return {"conservation": "no-records"}, False
    # Attribute the WARM wall (build wall subtracted) — the same
    # family_warm_wall_s rule `trace kern` renders, so the headline
    # JSON and the CLI agree on the identical artifact.
    att = attribution(ent, family_warm_wall_s(dispatch, FAM_PHOLD))
    block = {
        "conservation": "ok" if ok else
        f"VIOLATED: {problems[0] if problems else '?'}",
        "spans": ent["spans"],
        "micro_iters": ent["trips"],
        "occupancy_permille": {s: row["occupancy_permille"]
                               for s, row in att.items()},
        "us_per_host_round": {s: row["us_per_host_round"]
                              for s, row in att.items()},
    }
    return block, ok


def phold_rung() -> dict:
    """PHOLD scaling ladder (1k/8k/64k LPs): the device-resident
    multi-round loop (ops/phold_span.py, fused dispatch + donated
    resident carries) vs the C++ span path at every scale, with the
    per-dispatch floor, per-round walls, residency hit rate, and a
    rounds-per-dispatch x host-count crossover estimate — the
    device-vs-engine routing question as a modelled number.  Forced
    runs carry the device-kernel observatory (ISSUE 15): every
    recorded rung gets the per-stage occupancy + attributed
    us/host/round breakdown next to its wall, the crossover fit gets
    the attribution next to the fitted slope, and a rung whose kernel
    channel fails the fires-vs-micro_iters conservation check is
    REFUSED (recorded as such, excluded from the fit).  Returns the
    headline-JSON fragment."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.tools.netgen import phold_yaml

    def run_scale(n, stop, n_init, mean, peers=None, caps=None,
                  device_spans=None):
        text = phold_yaml(n, n_init=n_init, mean_delay_ns=mean,
                          stop_time=stop, seed=13, scheduler="tpu",
                          device_spans=device_spans,
                          peers_per_host=peers)
        cfg = ConfigOptions.from_yaml_text(text)
        if device_spans == "force":
            # Device-kernel observatory on every forced rung: the
            # per-stage breakdown is the rung's attribution record.
            cfg.experimental.kernel_observatory = "on"
        manager = Manager(cfg)
        if device_spans == "force" and caps:
            runner = manager.make_dev_span_runner()
            for k, v in caps.items():
                setattr(runner, k, v)
            manager._dev_span = runner
        for h in manager.hosts:
            h.set_tracing(False)
        t0 = time.perf_counter()
        summary = manager.run()
        return manager, summary, time.perf_counter() - t0

    # 64k needs bounded peer lists (a full 64k^2 peer matrix fits
    # nothing) and right-sized ring caps (the defaults carry a 2048-
    # deep CoDel ring per host — 64k hosts of that is pure waste at
    # PHOLD rates; the export refuses transactionally if ever wrong).
    # The crossover slope fit must vary ONLY the host count: fit
    # rungs (fit=True) pin peers/n_init/mean/caps to the 64k shape
    # (ring-16), while the display rungs keep their historical
    # workload shapes for cross-round comparability (the 1k rung is
    # the r5 141.0 s full-mesh comparator).
    def overlap_identity_pregate() -> bool:
        """Byte-identity pre-gate for the overlapped pipeline
        (ISSUE 16): two fully-traced runs at a small ladder shape,
        span_overlap on vs off, trace lines compared exactly.  The
        ladder's warm walls are only honest perf numbers if the
        double buffer provably changes NO simulation byte — a failed
        gate refuses every rung ("refused-identity")."""
        def traced(overlap: bool):
            text = phold_yaml(512, n_init=1, mean_delay_ns=20_000_000,
                              stop_time="0.3s", seed=13,
                              scheduler="tpu", device_spans="force",
                              peers_per_host=16)
            cfg = ConfigOptions.from_yaml_text(text)
            cfg.experimental.span_overlap = "on" if overlap else "off"
            mgr = Manager(cfg)
            mgr.run()
            return mgr.trace_lines()
        return traced(True) == traced(False)

    ring_caps = dict(CAP_I=32, CAP_T=16, CAP_R=64, CAP_S=64,
                     CAP_C=256, CAP_P=16)
    ladder = [
        ("1k", 1000, "0.5s", 2, 20_000_000, None, None, False),
        ("1k-ring", 1000, "0.5s", 1, 20_000_000, 16, ring_caps,
         True),
        # 8k full-mesh peer lists (8191) exceed the runner's CAP_P
        # (4096): the export refused on every attempt and the rung
        # silently measured nothing device-side — bounded ring peers
        # keep it inside the family's domain.
        ("8k", 8192, "0.3s", 1, 50_000_000, 64, None, False),
        ("64k", 65536, "0.15s", 1, 20_000_000, 16, ring_caps, True),
    ]
    frag: dict = {"rungs": {}}
    refused = False
    rows = []
    if not overlap_identity_pregate():
        print("bench[phold-ladder]: REFUSED — overlap byte-identity "
              "pre-gate failed (span_overlap on vs off traces "
              "diverge); no rung records", file=sys.stderr)
        for tag, *_rest in ladder:
            frag["rungs"][tag] = {"outcome": "refused-identity"}
        frag["refused"] = True
        frag["overlap_identity"] = "FAILED"
        return frag
    frag["overlap_identity"] = "byte-identical"
    for tag, n, stop, n_init, mean, peers, caps, fit in ladder:
        # comparator pinned to the engine path: "auto" could probe
        # the device mid-run with default caps at these host counts
        _mc, s_cpp, w_cpp = run_scale(n, stop, n_init, mean, peers,
                                      device_spans="off")
        del _mc   # only the walls/summary are used past this point
        # The first forced-device run pays XLA trace+compile (the
        # kernel cache is keyed on (H, P, caps), so every ladder
        # scale compiles fresh); a second in-process run reuses the
        # jitted kernel.  The slope fit needs the warm wall —
        # manager.py discards cold EWMA samples for the same reason.
        _m_cold, _s_cold, w_cold = run_scale(n, stop, n_init, mean,
                                             peers, caps, "force")
        # Release the cold manager (its runner pins the full resident
        # SoA) before the warm run — three live Managers at the 64k
        # rung is three 64k-host state sets at once.
        del _m_cold, _s_cold
        m, s, w_warm = run_scale(n, stop, n_init, mean, peers,
                                 caps, "force")
        w = w_warm
        r = m._dev_span
        if r is None or r.spans == 0:
            print(f"bench[phold-{tag}]: device spans did not run "
                  f"(spans={getattr(r, 'spans', 0)}, "
                  f"aborts={getattr(r, 'aborts', 0)}, "
                  f"ineligible={getattr(r, 'ineligible', 0)}, "
                  f"over_caps={getattr(r, 'over_caps', 0)}, "
                  f"sim_rounds={s.rounds})", file=sys.stderr)
            continue
        dev_round_ms = 1e3 * w / max(r.rounds, 1)
        cpp_round_ms = 1e3 * w_cpp / max(s_cpp.rounds, 1)
        kern_block, conserved = _kern_rung_block(m, r)
        if not conserved:
            # The kernel channel's conservation check failed: refuse
            # to record this rung in the fit (the refusal IS the
            # record) and fail the rung set.
            refused = True
            print(f"bench[phold-{tag}]: REFUSED — kernel-channel "
                  f"conservation failed "
                  f"({(kern_block or {}).get('conservation')})",
                  file=sys.stderr)
            frag["rungs"][tag] = {"outcome": "refused-conservation",
                                  "kern": kern_block}
            continue
        if fit:
            rows.append((n, dev_round_ms, cpp_round_ms))
        # The overlapped-pipeline block (ISSUE 16): the honest
        # record of whether the double buffer hid the host work at
        # this rung — device_idle_frac is the acceptance number.
        ov = r.overlap_summary()
        frag["rungs"][tag] = {
            "hosts": n,
            "dev_ms_per_round": round(dev_round_ms, 3),
            "cpp_ms_per_round": round(cpp_round_ms, 3),
            "device_rounds": r.rounds,
            "warm_wall_s": round(w, 2),
            "fit": fit,
            "kern": kern_block,
            "overlap": {
                "in_flight_windows": ov["windows"],
                "landed": ov["hits"],
                "refusals": ov["refusals"],
                "device_idle_frac": ov["device_idle_frac"],
                "host_idle_frac": ov["host_idle_frac"],
            },
        }
        print(f"bench[phold-{tag}]: {s.packets_sent} messages; device "
              f"{r.rounds}/{s.rounds} rounds "
              f"({r.spans} dispatches, {r.resident_hits} resident, "
              f"{r.micro_iters} micro-iters, aborts {r.aborts}) in "
              f"{w:.1f}s warm / {w_cold:.1f}s cold "
              f"[{dev_round_ms:.1f} ms/round, per-dispatch floor "
              f"{1e3 * w / r.spans:.0f} ms]; C++ span path "
              f"{s_cpp.packets_sent} msgs in {w_cpp:.1f}s "
              f"[{cpp_round_ms:.2f} ms/round]; overlap "
              f"{ov['windows']} windows / {ov['hits']} landed, "
              f"device idle {100.0 * ov['device_idle_frac']:.0f}%, "
              f"host idle {100.0 * ov['host_idle_frac']:.0f}%",
              file=sys.stderr)
        if kern_block:
            occ = kern_block.get("occupancy_permille", {})
            tops = ", ".join(
                f"{s} {v / 10:.1f}%" for s, v in sorted(
                    occ.items(), key=lambda kv: -kv[1])[:4])
            print(f"bench[phold-{tag}]: stage occupancy {tops}; "
                  f"conservation {kern_block['conservation']}",
                  file=sys.stderr)

    frag["refused"] = refused
    if len(rows) >= 2:
        # Linear per-round cost model c(H) = a + b*H from the
        # shape-pinned fit rungs (identical peers/n_init/mean/caps,
        # only H varies): the device wins once its (flatter) slope
        # beats the C++ path's — on the CPU backend both slopes are
        # host-bound, so "no crossover" is itself the measured,
        # recorded answer (BASELINE.md cost model).
        (h0, d0, c0), (h1, d1, c1) = rows[0], rows[-1]
        b_dev = (d1 - d0) / (h1 - h0)
        b_cpp = (c1 - c0) / (h1 - h0)
        a_dev = d0 - b_dev * h0
        a_cpp = c0 - b_cpp * h0
        # The attributed per-stage breakdown of the LARGEST fit rung
        # sits next to the fitted slope in the headline JSON: the
        # overlap/pallas work (ROADMAP item 3) gets a before/after
        # per stage, not just one number.
        big = next((frag["rungs"][t] for t in ("64k", "1k-ring")
                    if t in frag["rungs"]
                    and frag["rungs"][t].get("hosts") == h1), None)
        frag["crossover"] = {
            "dev_us_per_host": round(1e3 * b_dev, 3),
            "cpp_us_per_host": round(1e3 * b_cpp, 3),
            "dev_floor_ms": round(a_dev, 3),
            "cpp_floor_ms": round(a_cpp, 3),
            "stage_us_per_host_round": (big or {}).get(
                "kern", {}).get("us_per_host_round", {}),
        }
        if b_dev < b_cpp:
            hx = (a_dev - a_cpp) / (b_cpp - b_dev)
            frag["crossover"]["modelled_crossover_hosts"] = round(hx)
            print(f"bench[phold-crossover]: device per-round slope "
                  f"{1e3 * b_dev:.2f} us/host vs C++ "
                  f"{1e3 * b_cpp:.2f} us/host -> modelled crossover "
                  f"~{hx:,.0f} hosts", file=sys.stderr)
        else:
            print(f"bench[phold-crossover]: none on this backend — "
                  f"device per-round slope {1e3 * b_dev:.2f} us/host "
                  f">= C++ {1e3 * b_cpp:.2f} us/host (device floor "
                  f"{a_dev:.1f} ms vs C++ {a_cpp:.2f} ms); the "
                  f"batched path needs lane-parallel hardware to win",
                  file=sys.stderr)

    # udp-mesh family on the device loop (dual-thread apps, saturated
    # send buffers, loss) — a paced 24-host mesh so the sim spans many
    # windows (the full bench[mesh-100] burst collapses into a handful
    # of giant rounds, which the C++ engine already serves best).
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    try:
        from test_phold_span import mesh_cfg
    except ImportError as e:
        print(f"bench[mesh-dev]: skipped ({e})", file=sys.stderr)
        return frag
    def run_mesh():
        t0 = time.perf_counter()
        cfg = mesh_cfg("tpu", n=24, device_spans="force")
        cfg.experimental.kernel_observatory = "on"
        mgr = Manager(cfg)
        for h in mgr.hosts:
            h.set_tracing(False)
        sm = mgr.run()
        return mgr, sm, time.perf_counter() - t0

    # Same cold/warm split as the ladder: the second in-process run
    # reuses the jitted kernel, so its wall is the steady state.
    _mgr_cold, _sm_cold, w_cold = run_mesh()
    mgr, sm, w_warm = run_mesh()
    w = w_warm
    r = mgr._dev_span
    share = 100.0 * r.rounds / max(sm.rounds, 1)
    kern_block, conserved = _kern_rung_block(mgr, r)
    if not conserved:
        frag["refused"] = True
        frag["rungs"]["mesh-dev"] = {
            "outcome": "refused-conservation", "kern": kern_block}
        print(f"bench[mesh-dev]: REFUSED — kernel-channel "
              f"conservation failed "
              f"({(kern_block or {}).get('conservation')})",
              file=sys.stderr)
        return frag
    frag["rungs"]["mesh-dev"] = {
        "hosts": 24,
        "device_rounds": r.rounds,
        "warm_wall_s": round(w, 2),
        "kern": kern_block,
    }
    print(f"bench[mesh-dev]: 24-host udp-mesh, {sm.packets_sent} "
          f"packets; device multi-round {r.rounds}/{sm.rounds} rounds "
          f"on device ({share:.0f}%, {r.spans} dispatches, "
          f"{r.resident_hits} resident, aborts {r.aborts}) in "
          f"{w:.1f}s warm / {w_cold:.1f}s cold", file=sys.stderr)
    return frag


def tcp_dev_rung() -> None:
    """TCP steady-stream device-span rung (ISSUE 1 tentpole): the
    fixed-connection tgen tier with forced device spans — whole
    conservative windows of per-connection TCP state (cwnd, SACK,
    RTO/delack timers) stepped inside the lax.while_loop, reported as
    the device-round share."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.tools.netgen import tcp_stream_yaml

    def run(device_spans=None):
        text = tcp_stream_yaml(64, n_servers=8, nbytes=50_000_000,
                               loss=0.005, stop_time="2s", seed=11,
                               scheduler="tpu",
                               device_spans=device_spans)
        manager = Manager(ConfigOptions.from_yaml_text(text))
        for h in manager.hosts:
            h.set_tracing(False)
        t0 = time.perf_counter()
        summary = manager.run()
        return manager, summary, time.perf_counter() - t0

    _mc, s_cpp, w_cpp = run()
    m, s, w = run("force")
    r = m._dev_span_tcp
    if r is None or r.spans == 0:
        print(f"bench[tcp-dev]: device spans did not run "
              f"(spans={getattr(r, 'spans', 0)}, aborts="
              f"{getattr(r, 'aborts', 0)}, transient="
              f"{getattr(r, 'over_caps', 0)})", file=sys.stderr)
        return
    share = 100.0 * r.rounds / max(s.rounds, 1)
    print(f"bench[tcp-dev]: 64-host TCP stream tier, "
          f"{s.packets_sent} packets ({s.packets_dropped} dropped on "
          f"lossy edges); device multi-round {r.rounds}/{s.rounds} "
          f"rounds on device ({share:.0f}%, {r.spans} dispatches, "
          f"aborts {r.aborts}) in {w:.1f}s; C++ span path "
          f"{s_cpp.packets_sent} pkts in {w_cpp:.1f}s", file=sys.stderr)


# ---------------------------------------------------------------------
# Sharded rungs (ISSUE 11): the shard-count scaling curve, the standing
# sharded 100k rung, the leaf-spine rack rung and the 1M stretch.  Each
# runs in a SUBPROCESS on a virtual 8-device CPU mesh (a process can
# only initialize one platform, and the heavy rungs must not bloat the
# parent) and prints ONE JSON line on stdout that the parent records in
# the headline JSON.  Every sharded record is gated on trace
# byte-identity: a rung that cannot prove its bytes refuses to record.
# ---------------------------------------------------------------------

def sharded_fragment(flag: str, timeout_s: int) -> dict | None:
    import subprocess
    env = dict(os.environ)
    if not os.environ.get("PROBE_REAL_TPU"):
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = \
                (flags + " --xla_force_host_platform_device_count=8"
                 ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), flag],
            env=env, stdout=subprocess.PIPE, text=True,
            timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench[{flag.lstrip('-')}]: timed out ({timeout_s}s)",
              file=sys.stderr)
        return {"outcome": f"timeout after {timeout_s}s"}
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return {"outcome": f"failed (exit {proc.returncode})"}


def identity_gate_10k(n_hosts: int = 2000) -> bool:
    """The sharded record gate: scripts/verify_10k_sharded.py at
    reduced scale — full packet tracing, serial vs tpu_shards=8,
    SHA-256 over every trace line.  False = refuse to record."""
    import subprocess
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "verify_10k_sharded.py")
    try:
        proc = subprocess.run(
            [sys.executable, script, str(n_hosts)], env=dict(os.environ),
            capture_output=True, text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        print("bench[sharded-identity]: gate timed out", file=sys.stderr)
        return False
    for line in (proc.stdout or "").strip().splitlines():
        print(f"  identity: {line}", file=sys.stderr)
    return proc.returncode == 0 and "BYTE-IDENTICAL" in proc.stdout


def sharded_curve_main() -> None:
    """--sharded-10k entry: the 1/2/4/8 shard-count scaling curve for
    the 10k rung.  With spans the default routed path for tpu_shards >
    1, the sharded rungs route engine-pure stretches through the span
    ladder exactly like single-shard — the curve records honestly how
    much the residual per-round exchange costs at each width.  Records
    only behind the trace byte-identity gate."""
    if not identity_gate_10k():
        print("bench[10k-sharded]: trace byte-identity FAILED — "
              "refusing to record the sharded curve", file=sys.stderr)
        print(json.dumps({"identity": "FAILED"}), flush=True)
        return
    curve = {}
    for shards in (1, 2, 4, 8):
        build = (lambda sh: lambda s: config_10k(
            s, **({"tpu_shards": sh} if sh > 1 else {})))(shards)
        # Best-of-2 with the exchange stats snapshotted PER TRIAL, so
        # the recorded row never mixes the best trial's wall with
        # another trial's exchange telemetry.
        best = None
        for trial in range(2):
            summary, wall = run_once(
                build, "tpu",
                report_routes=(f"10k-sharded-{shards}"
                               if trial == 1 else None))
            if best is None or wall < best[1]:
                best = (summary, wall, LAST_RUN.get("exchange"))
        summary, wall, exchange = best
        cov = 100.0 * summary.span_rounds / max(summary.rounds, 1)
        row = {
            "wall_s": round(wall, 2),
            "sim_s_per_wall_s": round(
                summary.busy_end_ns / 1e9 / wall, 3),
            "packets": summary.packets_sent,
            "span_coverage_pct": round(cov, 1),
        }
        if exchange is not None:
            row["exchange"] = exchange
        curve[str(shards)] = row
    sizes = {r["packets"] for r in curve.values()}
    if len(sizes) != 1:
        print(f"bench[10k-sharded]: shard counts disagreed on "
              f"workload size {sorted(sizes)} — refusing to record",
              file=sys.stderr)
        print(json.dumps({"identity": "FAILED-workload-size"}),
              flush=True)
        return
    ratio = (curve["8"]["sim_s_per_wall_s"]
             / max(curve["1"]["sim_s_per_wall_s"], 1e-9))
    print(f"bench[10k-sharded]: {curve['8']['packets']} packets, "
          f"{curve['8']['sim_s_per_wall_s']:.3f} sim-s/wall-s "
          f"({curve['8']['wall_s']}s wall, tpu_shards=8, "
          f"virtual-8-cpu devices); 8-shard vs single-shard "
          f"{ratio:.3f}x; curve 1/2/4/8 = "
          + "/".join(f"{curve[k]['sim_s_per_wall_s']:.3f}"
                     for k in ("1", "2", "4", "8")), file=sys.stderr)
    print(json.dumps({
        "identity": "ok (2000-host traced serial-vs-sharded8)",
        "curve": curve,
        "sharded8_vs_single_shard": round(ratio, 3),
    }), flush=True)


def sharded_100k_main() -> None:
    """--sharded-100k entry: bench[scale-100k-sharded] — 100k PHOLD
    LPs with the host axis over tpu_shards=8, FULL packet tracing on
    BOTH sides, SHA-256 trace identity vs the single-shard engine
    baseline asserted before anything records (symmetric traced walls,
    so the recorded ratio is apples-to-apples)."""
    import hashlib

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.tools.netgen import phold_args
    n = 100_000
    names = [f"lp{i:06d}" for i in range(n)]
    hosts = {}
    for i, name in enumerate(names):
        hosts[name] = {"network_node_id": 0, "processes": [{
            "path": "phold",
            "args": phold_args(i, names, 1, 20_000_000,
                               peers_per_host=8),
            "start_time": "100ms",
            "expected_final_state": "running"}]}

    def build(shards):
        exp = {"scheduler": "tpu", "tpu_device_spans": "off"}
        if shards > 1:
            exp["tpu_shards"] = shards
        return ConfigOptions.from_dict({
            "general": {"stop_time": "0.3s", "seed": 13},
            "network": {"graph": {"type": "gml", "inline": """
graph [ node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "5 ms" ] ]"""}},
            "experimental": exp,
            "hosts": hosts})

    rows = {}
    for label, shards in (("baseline", 1), ("sharded8", 8)):
        t0 = time.perf_counter()
        mgr = Manager(build(shards))
        build_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        summary = mgr.run()
        wall = time.perf_counter() - t0
        h = hashlib.sha256()
        lines = 0
        for line in mgr.trace_lines():
            h.update(line.encode())
            h.update(b"\n")
            lines += 1
        cov = 100.0 * summary.span_rounds / max(summary.rounds, 1)
        rows[label] = {
            "wall_s": round(wall, 2), "build_s": round(build_s, 2),
            "events": summary.events,
            "events_per_s": round(summary.events / wall),
            "span_coverage_pct": round(cov, 1),
            "trace_lines": lines, "digest": h.hexdigest(),
        }
        print(f"bench[scale-100k-sharded]: {label} {wall:.1f}s wall "
              f"({summary.events} events, {lines} trace lines, span "
              f"coverage {cov:.0f}%)", file=sys.stderr)
        del mgr
    if rows["baseline"]["digest"] != rows["sharded8"]["digest"]:
        print("bench[scale-100k-sharded]: trace DIVERGED from the "
              "engine baseline — refusing to record", file=sys.stderr)
        print(json.dumps({"identity": "FAILED"}), flush=True)
        return
    for r in rows.values():
        del r["digest"]
    print(f"bench[scale-100k-sharded]: {n} hosts byte-identical to "
          f"the engine baseline ({rows['sharded8']['trace_lines']} "
          f"trace lines); sharded {rows['sharded8']['wall_s']}s vs "
          f"baseline {rows['baseline']['wall_s']}s (tracing on, both "
          f"sides)", file=sys.stderr)
    print(json.dumps({
        "hosts": n,
        "identity": "ok (sha256 over every trace line, tracing on)",
        "baseline": rows["baseline"],
        "sharded8": rows["sharded8"],
    }), flush=True)


def sharded_leaf_spine_main() -> None:
    """--sharded-leafspine entry: the PR 9 leaf-spine ECMP fabric at
    rack-scale host counts on the sharded path — 8 racks x 64 hosts of
    cross-rack tgen TCP over tpu_shards=8, fabric byte-conservation
    and FCT records enforced, trace identity vs the single-shard
    engine run asserted (shard layout must not touch fabric bytes)."""
    import hashlib

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.tools.netgen import leaf_spine_yaml

    def run(shards):
        cfg = ConfigOptions.from_yaml_text(leaf_spine_yaml(
            n_leaf=8, hosts_per_leaf=64, n_spine=4, nbytes=500_000,
            count=1, stop_time="3s", seed=23, scheduler="tpu"))
        if shards > 1:
            cfg.experimental.tpu_shards = shards
        mgr = Manager(cfg)
        t0 = time.perf_counter()
        summary = mgr.run()
        wall = time.perf_counter() - t0
        h = hashlib.sha256()
        for line in mgr.trace_lines():
            h.update(line.encode())
            h.update(b"\n")
        return mgr, summary, wall, h.hexdigest()

    m1, s1, w1, d1 = run(1)
    m8, s8, w8, d8 = run(8)
    if d1 != d8:
        print("bench[leaf-spine-sharded]: trace DIVERGED across shard "
              "counts — refusing to record", file=sys.stderr)
        print(json.dumps({"identity": "FAILED"}), flush=True)
        return
    cons = m8.fabric_conservation()
    if cons["violations"] != 0:
        print(f"bench[leaf-spine-sharded]: fabric conservation "
              f"violated ({cons['violations']}) — refusing to record",
              file=sys.stderr)
        print(json.dumps({"identity": "FAILED-conservation"}),
              flush=True)
        return
    fab = m8.fabric_summary(s8.busy_end_ns)
    cov = 100.0 * s8.span_rounds / max(s8.rounds, 1)
    fct = fab.get("fct", {})
    print(f"bench[leaf-spine-sharded]: 512 hosts, 8x64 racks, "
          f"{s8.packets_sent} packets in {w8:.1f}s (single-shard "
          f"{w1:.1f}s), span coverage {cov:.0f}%, conservation exact, "
          f"fct p99 "
          f"{fct.get('p99_ns', 0) / 1e6:.1f}ms ({fct.get('flows', 0)} "
          f"flows), byte-identical across shard counts",
          file=sys.stderr)
    print(json.dumps({
        "hosts": 512, "identity": "ok (vs single-shard engine run)",
        "packets": s8.packets_sent,
        "wall_s": round(w8, 2), "single_shard_wall_s": round(w1, 2),
        "span_coverage_pct": round(cov, 1),
        "conservation": "ok",
        "peak_queue_depth": fab["peak_queue_depth"],
        "fct": fct,
    }), flush=True)


def sharded_1m_main() -> None:
    """--sharded-1m entry: the 1M-host stretch rung ("millions of
    users" territory, ROADMAP item 1).  Attempted with guardrails; the
    OUTCOME records honestly — wall + memory on success, the failure
    mode otherwise."""
    import resource

    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.tools.netgen import phold_args
    n = 1_000_000
    frag = {"hosts": n}
    try:
        names = [f"lp{i:07d}" for i in range(n)]
        hosts = {}
        for i, name in enumerate(names):
            hosts[name] = {"network_node_id": 0, "processes": [{
                "path": "phold",
                "args": phold_args(i, names, 1, 20_000_000,
                                   peers_per_host=4),
                "start_time": "100ms",
                "expected_final_state": "running"}]}
        cfg = ConfigOptions.from_dict({
            "general": {"stop_time": "0.15s", "seed": 13},
            "network": {"graph": {"type": "gml", "inline": """
graph [ node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "5 ms" ] ]"""}},
            "experimental": {"scheduler": "tpu",
                             "tpu_device_spans": "off",
                             "tpu_shards": 8},
            "hosts": hosts})
        t0 = time.perf_counter()
        mgr = Manager(cfg)
        build_s = time.perf_counter() - t0
        for h in mgr.hosts:
            h.set_tracing(False)
        t0 = time.perf_counter()
        summary = mgr.run()
        wall = time.perf_counter() - t0
        rss_gb = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / (1 << 20)
        cov = 100.0 * summary.span_rounds / max(summary.rounds, 1)
        frag.update({
            "outcome": "ok",
            "build_s": round(build_s, 1), "wall_s": round(wall, 1),
            "events": summary.events,
            "events_per_s": round(summary.events / wall),
            "span_coverage_pct": round(cov, 1),
            "peak_rss_gb": round(rss_gb, 2),
        })
        print(f"bench[scale-1m-sharded]: {n} hosts, {summary.events} "
              f"events in {wall:.1f}s (build {build_s:.1f}s, "
              f"{frag['events_per_s']:,} events/s, span coverage "
              f"{cov:.0f}%, peak RSS {rss_gb:.1f} GB)",
              file=sys.stderr)
    except MemoryError:
        rss_gb = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / (1 << 20)
        frag.update({"outcome": "MemoryError",
                     "peak_rss_gb": round(rss_gb, 2)})
        print(f"bench[scale-1m-sharded]: MemoryError at "
              f"{rss_gb:.1f} GB RSS — honest failure recorded",
              file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the outcome IS the record
        frag.update({"outcome": f"{type(e).__name__}: {e}"})
        print(f"bench[scale-1m-sharded]: failed: {e}", file=sys.stderr)
    print(json.dumps(frag), flush=True)


def managed_rung() -> dict | None:
    """>=100 REAL OS processes under the shim simultaneously (the
    reference's headline emulation capability, README.md:19-22): 8 C
    UDP echo servers + 120 C clients as native processes — LD_PRELOAD
    shim, seccomp trap-all, shmem IPC, syscall emulation all inside the
    measured window.  The 10k rung above measures the *simulator*; this
    one measures the *emulator*.

    Syscall observatory (ISSUE 7 / ROADMAP item 2's acceptance
    metric): the RECORDED rung runs observatory-OFF (comparable to the
    pre-observatory baseline — the off path must cost nothing); a
    separate wall-profiled run supplies the IPC round-trip breakdown.
    syscalls_per_sec and the (always-on) disposition histogram come
    from the recorded run.  Returns the headline-JSON fragment."""
    import shutil
    import tempfile
    if shutil.which("cc") is None:
        print("bench[managed-128]: skipped (no C toolchain)",
              file=sys.stderr)
        return None
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    try:
        import test_managed_scale as tms
    except ImportError as e:  # pytest absent in a bare deployment
        print(f"bench[managed-128]: skipped ({e})", file=sys.stderr)
        return None
    with tempfile.TemporaryDirectory() as td:
        from shadow_tpu.tools.netgen import compile_echo_binaries
        bins = compile_echo_binaries(td)
        from shadow_tpu.core.manager import run_simulation

        def run_managed(scheduler, native, observatory="off",
                        svc=None):
            cfg = tms.scale_config(bins)
            cfg.experimental.scheduler = scheduler
            cfg.experimental.native_dataplane = native
            cfg.experimental.syscall_observatory = observatory
            if svc is not None:
                cfg.experimental.syscall_service_plane = svc
            t0 = time.perf_counter()
            manager, summary = run_simulation(cfg)
            return manager, summary, time.perf_counter() - t0

        # Comparator (VERDICT r5 missing #3): the SAME emulation
        # workload under python thread_per_core and the engine-backed
        # variant, so the emulator path's perf can ratchet instead of
        # floating as a single uncomparable number.
        _mb, sb, wall_base = run_managed("thread_per_core", "off")
        manager, summary, wall = run_managed("thread_per_core", "on")
        # Wall-profiled companion run: where one syscall round trip's
        # wall goes (IPC wait vs dispatch vs resume vs memcopy).
        m_obs, s_obs, wall_obs = run_managed("thread_per_core", "on",
                                             observatory="wall")
        # Service-plane comparator (ISSUE 13): the recorded rung runs
        # with the plane on its default (auto); one svc=off run shows
        # what the host-affine drain is worth — on oversubscribed
        # boxes the stealing pool can enter a futex-thrash mode the
        # plane avoids, so the ratio is the honest spread, not noise.
        _msvc, ssvc, wall_svc_off = run_managed(
            "thread_per_core", "on", svc="off")
        n_procs = sum(len(h.processes) for h in manager.hosts)
        ok = summary.ok and sb.ok and s_obs.ok and ssvc.ok
        sim_s = summary.busy_end_ns / 1e9
        syscalls_per_sec = summary.syscalls / wall if wall > 0 else 0.0
        disp = manager.sc_disposition_totals()
        ipc = m_obs.sctrace.wall_summary()
        mc = ipc["memcopy"]
        print(f"bench[managed-128]: {n_procs} real processes under the "
              f"shim, {summary.packets_sent} packets, "
              f"{summary.syscalls} syscalls emulated, engine-tpc "
              f"{sim_s / wall:.3f} sim-s/wall-s ({wall:.1f}s wall), "
              f"python-tpc {sb.busy_end_ns / 1e9 / wall_base:.3f} "
              f"sim-s/wall-s ({wall_base:.1f}s wall), vs_baseline "
              f"{wall_base / wall:.3f}, ok={ok}", file=sys.stderr)
        disp_s = ", ".join(f"{k} {v}" for k, v in sorted(
            disp.items(), key=lambda kv: -kv[1])) or "none"
        print(f"syscalls: {summary.syscalls} emulated, "
              f"{syscalls_per_sec:,.0f}/s | {disp_s} | ipc wall: wait "
              f"{ipc['wait_ns'] / 1e9:.2f}s, dispatch "
              f"{ipc['dispatch_ns'] / 1e9:.2f}s, resume "
              f"{ipc['resume_ns'] / 1e9:.2f}s, memcopy "
              f"{(mc['read_ns'] + mc['write_ns']) / 1e9:.2f}s "
              f"({wall_obs:.1f}s wall observatory-on, overhead "
              f"{100.0 * (wall_obs - wall) / wall:+.1f}%)",
              file=sys.stderr)
        # Overhead guard (ISSUE 7 acceptance): what CAN be asserted
        # in-run is that the instrumentation itself is within noise —
        # the wall-profiled run must not be measurably slower than the
        # observatory-off run (loose bound: single-trial walls on a
        # shared box swing +-20%).  The "off rung within noise of the
        # pre-PR baseline" half of the criterion is a cross-run
        # comparison: observatory_off_wall_s IS the recorded headline
        # wall, diffed against BENCH_r* history by the driver.
        assert wall_obs <= wall * 1.5, \
            (f"instrumented wall {wall_obs:.1f}s > 1.5x observatory-"
             f"off wall {wall:.1f}s — observatory overhead regressed")
        return {
            "processes": n_procs,
            "sim_s_per_wall_s": round(sim_s / wall, 3),
            "vs_baseline": round(wall_base / wall, 3),
            "syscalls": summary.syscalls,
            "syscalls_per_sec": round(syscalls_per_sec),
            "dispositions": disp,
            "ipc_wall_s": {
                "wait": round(ipc["wait_ns"] / 1e9, 3),
                "dispatch": round(ipc["dispatch_ns"] / 1e9, 3),
                "resume": round(ipc["resume_ns"] / 1e9, 3),
                "memcopy": round((mc["read_ns"] + mc["write_ns"])
                                 / 1e9, 3),
            },
            "observatory_off_wall_s": round(wall, 3),
            "observatory_wall_wall_s": round(wall_obs, 3),
            # Syscall service plane (ISSUE 13): wall of the same
            # workload with the plane forced off, and the resulting
            # ratio (>1 = the plane helped).
            "svc_off_wall_s": round(wall_svc_off, 3),
            "svc_speedup": round(wall_svc_off / wall, 3)
            if wall > 0 else 0.0,
            "svc": (manager.svc.wall_summary()
                    if manager.svc is not None else None),
            "ok": ok,
        }


def chaos_managed_rung() -> dict | None:
    """`bench[chaos-managed-128]` (docs/ROBUSTNESS.md): a managed-128
    fleet with an INJECTED mid-run segfault and a hung binary, run
    under `on_failure: quarantine` with the hang watchdog armed.  The
    rung REFUSES to record unless (a) the run completes end to end
    with no sim abort and no plugin error, (b) drop-cause
    conservation is exact, and (c) re-running with the recorded fault
    ledger supplied as a `faults:` schedule is byte-identical (packet
    trace, drop attribution, syscall dispositions, ledger)."""
    import shutil
    import subprocess
    import tempfile
    if shutil.which("cc") is None:
        print("bench[chaos-managed-128]: skipped (no C toolchain)",
              file=sys.stderr)
        return None
    plug_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tests", "plugins")
    with tempfile.TemporaryDirectory() as td:
        from shadow_tpu.tools.netgen import compile_echo_binaries
        bins = compile_echo_binaries(td)
        chaos_bins = {}
        for name in ("crash_mid", "hang_forever"):
            out = os.path.join(td, name)
            subprocess.run(
                ["cc", "-O1", "-o", out,
                 os.path.join(plug_dir, name + ".c")], check=True)
            chaos_bins[name] = out
        from shadow_tpu.core.config import (FaultConfig, HostConfig,
                                            ProcessConfig)
        from shadow_tpu.core.manager import run_simulation

        def chaos_cfg(faults=None):
            cfg = _managed_fleet_config(bins, 128, stop_time="20s")
            cfg.experimental.scheduler = "thread_per_core"
            cfg.experimental.native_dataplane = "on"
            cfg.experimental.managed_watchdog_ns = 2_000_000_000
            # Dedicated chaos hosts (the echo fleet's own servers
            # count exact echo budgets, so killing a fleet member
            # would strand an innocent peer into a plugin error) plus
            # a background internal-app pinger pair that keeps round
            # boundaries alive well past the failure instants — a
            # quarantine needs a next boundary to land on.
            cfg.hosts["zbg0"] = HostConfig(
                name="zbg0", network_node_id=0, processes=[
                    ProcessConfig(path="udp-echo-server",
                                  args=["9100"],
                                  start_time_ns=1_000_000_000,
                                  expected_final_state="running")])
            cfg.hosts["zbg1"] = HostConfig(
                name="zbg1", network_node_id=0, processes=[
                    ProcessConfig(path="udp-pinger",
                                  args=["zbg0", "9100", "600"],
                                  start_time_ns=2_000_000_000,
                                  expected_final_state="exited 0")])
            for i, binary in ((0, "crash_mid"), (1, "hang_forever")):
                # Each chaos host also streams pings so its death
                # leaves in-flight traffic to drop host-down.
                cfg.hosts[f"zchaos{i}"] = HostConfig(
                    name=f"zchaos{i}", network_node_id=0, processes=[
                        ProcessConfig(path="udp-pinger",
                                      args=["zbg0", "9100", "600"],
                                      start_time_ns=2_000_000_000,
                                      expected_final_state="any"),
                        ProcessConfig(path=chaos_bins[binary],
                                      start_time_ns=5_000_000_000,
                                      expected_final_state="exited 0",
                                      on_failure="quarantine")])
            if faults:
                cfg.faults = [
                    FaultConfig(at_ns=int(op["at"].split()[0]),
                                action="quarantine", host=op["host"])
                    for op in faults]
            return cfg

        t0 = time.perf_counter()
        m1, s1 = run_simulation(chaos_cfg())
        wall = time.perf_counter() - t0
        led1 = m1.containment.ledger()
        drops1 = m1.drop_cause_totals()
        conserved = ("unattributed" not in drops1
                     and sum(drops1.values()) == s1.packets_dropped)
        causes = sorted(e["cause"] for e in led1["events"])
        if not s1.ok or not conserved or len(led1["ops"]) != 2 \
                or drops1.get("host-down", 0) < 1 \
                or causes != ["binary-death", "hang-watchdog"]:
            print(f"bench[chaos-managed-128]: REFUSED to record "
                  f"(ok={s1.ok}, conserved={conserved}, "
                  f"ops={led1['ops']}, causes={causes})",
                  file=sys.stderr)
            return {"outcome": "refused", "ok": False}
        m2, s2 = run_simulation(chaos_cfg(faults=led1["ops"]))
        led2 = m2.containment.ledger()
        identical = (m1.trace_lines() == m2.trace_lines()
                     and drops1 == m2.drop_cause_totals()
                     and m1.sc_disposition_totals()
                     == m2.sc_disposition_totals()
                     and led1["ops"] == led2["ops"])
        if not identical or not s2.ok:
            print("bench[chaos-managed-128]: REFUSED to record "
                  "(ledger replay NOT byte-identical)",
                  file=sys.stderr)
            return {"outcome": "replay-divergence", "ok": False}
        frag = {
            "outcome": "ok",
            "ok": True,
            "processes": sum(len(h.processes) for h in m1.hosts),
            "quarantines": len(led1["ops"]),
            "causes": causes,
            "drop_causes": drops1,
            "sim_s_per_wall_s": round(s1.busy_end_ns / 1e9 / wall, 3),
            "wall_s": round(wall, 1),
            "ledger_replay": "byte-identical",
        }
        print(f"bench[chaos-managed-128]: crash+hang contained "
              f"({causes}), {frag['quarantines']} quarantines, "
              f"drop conservation exact, ledger replay "
              f"byte-identical, {frag['sim_s_per_wall_s']} "
              f"sim-s/wall-s ({wall:.1f}s wall)", file=sys.stderr)
        return frag


def _managed_fleet_config(bins, n_procs: int, seed: int = 3,
                          stop_time: str = "30s"):
    """N-process managed-fleet config (the managed-1k/10k rungs;
    shared generator with `./setup managed`)."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.tools.netgen import managed_fleet_yaml
    return ConfigOptions.from_yaml_text(managed_fleet_yaml(
        bins["udp_echo_server"], bins["udp_echo_client"], n_procs,
        stop_time=stop_time, seed=seed))


def managed_scale_rung(n_procs: int, label: str,
                       record_outcome: bool = False) -> dict | None:
    """`bench[managed-1k]` standing rung / `managed-10k` stretch
    (ISSUE 13, ROADMAP item 2): n_procs REAL binaries under the shim
    with the syscall service plane on its default (auto), recording
    sim-s/wall-s + syscalls_per_sec.  With record_outcome the rung
    never raises — the outcome string (EMFILE at spawn, timeout,
    MemoryError…) IS the record, like the 1M stretch; the try covers
    the compile step AND the tempdir teardown, because a run that
    exhausted fds can make either fail and that failure mode must
    land in the record, not crash the bench."""
    import tempfile

    from shadow_tpu.tools.netgen import compile_echo_binaries
    frag: dict = {"processes": n_procs}
    try:
        with tempfile.TemporaryDirectory() as td:
            bins = compile_echo_binaries(td)
            if bins is None:
                print(f"bench[{label}]: skipped (no C toolchain)",
                      file=sys.stderr)
                return None
            from shadow_tpu.core.manager import run_simulation
            cfg = _managed_fleet_config(bins, n_procs)
            cfg.experimental.scheduler = "thread_per_core"
            cfg.experimental.native_dataplane = "on"
            t0 = time.perf_counter()
            manager, summary = run_simulation(cfg)
            wall = time.perf_counter() - t0
            sim_s = summary.busy_end_ns / 1e9
            frag.update({
                "outcome": "ok" if summary.ok else
                           f"plugin errors: "
                           f"{summary.plugin_errors[:2]}",
                "sim_s_per_wall_s": round(sim_s / wall, 3),
                "wall_s": round(wall, 1),
                "syscalls": summary.syscalls,
                "syscalls_per_sec": round(summary.syscalls / wall)
                if wall > 0 else 0,
                "svc": (manager.svc.wall_summary()
                        if manager.svc is not None else None),
            })
            print(f"bench[{label}]: {n_procs} real processes, "
                  f"{summary.syscalls} syscalls "
                  f"({frag['syscalls_per_sec']:,}/s), "
                  f"{frag['sim_s_per_wall_s']} sim-s/wall-s "
                  f"({wall:.1f}s wall), outcome {frag['outcome']}",
                  file=sys.stderr)
            if not summary.ok and not record_outcome:
                raise RuntimeError(frag["outcome"])
    except Exception as e:  # noqa: BLE001 — the outcome IS the record
        if not record_outcome:
            raise
        frag["outcome"] = f"{type(e).__name__}: {e}"[:300]
        print(f"bench[{label}]: outcome recorded honestly: "
              f"{frag['outcome']}", file=sys.stderr)
    return frag


def incast_rung(tcp: dict | None = None,
                label: str = "incast-32",
                nbytes: int = 500_000,
                stop_time: str = "3s") -> dict | None:
    """N->1 fan-in smoke (netgen.incast_yaml; ISSUE 8): queue buildup
    at the sink's inbound CoDel queue with the byte-conservation gate
    enforced, recorded in the headline JSON with peak queue depth and
    the FCT percentiles.  `tcp` threads the per-host congestion
    controller through (ISSUE 10: the incast-ecn rung runs this under
    {"cc": "dctcp", "ecn": "on"}).  Engine path, seconds of wall —
    safe ahead of the headline print."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.tools.netgen import incast_yaml

    cfg = ConfigOptions.from_yaml_text(
        incast_yaml(32, nbytes=nbytes, stop_time=stop_time,
                    scheduler="tpu", tcp=tcp))
    cfg.experimental.flight_recorder = "wall"
    manager = Manager(cfg)
    for h in manager.hosts:
        h.set_tracing(False)
    t0 = time.perf_counter()
    summary = manager.run()
    wall = time.perf_counter() - t0
    assert summary.ok, summary.plugin_errors[:3]
    fabric = manager.fabric_summary(summary.busy_end_ns)
    if fabric["conservation"] != "ok":
        raise AssertionError(
            f"incast byte conservation violated: "
            f"{fabric['conservation']}")
    fct = fabric.get("fct", {})
    print(f"bench[{label}]: {summary.packets_sent} packets in "
          f"{wall:.1f}s wall, peak queue "
          f"{fabric['peak_queue_depth']}, "
          f"marks {fabric.get('marked_pkts', 0)}, "
          f"fct p50/p99/p999 {fct.get('p50_ns', 0) / 1e6:.0f}/"
          f"{fct.get('p99_ns', 0) / 1e6:.0f}/"
          f"{fct.get('p999_ns', 0) / 1e6:.0f} ms, conservation ok",
          file=sys.stderr)
    return {"fan_in": 32, "wall_s": round(wall, 3),
            "packets": summary.packets_sent, "fabric": fabric}


def incast_ecn_rung() -> dict | None:
    """Standing DCTCP rung (ISSUE 10): a COMPLETION-SIZED 32->1
    incast (100 KB responses — every flow finishes inside the run, so
    FCT measures the fan-in tail, not the bottleneck's bandwidth) run
    twice, drop-based reno vs `tcp: {cc: dctcp, ecn: on}`, and the
    two FCT p99s recorded side by side in the headline JSON.  CE
    marks must be NONZERO on the dctcp leg (the marking law fired)
    and conservation must hold exactly on both runs (incast_rung
    refuses to return numbers otherwise) — the claim DCTCP exists to
    make, congestion signaled by marks instead of drops cuts the
    fan-in tail, as a measured number."""
    drop = incast_rung(label="incast-ecn-32/drop-based",
                       nbytes=100_000, stop_time="4s")
    ecn = incast_rung(tcp={"cc": "dctcp", "ecn": "on"},
                      label="incast-ecn-32/dctcp",
                      nbytes=100_000, stop_time="4s")
    if drop is None or ecn is None:
        return None
    marks = ecn["fabric"].get("marked_pkts", 0)
    if marks <= 0:
        raise AssertionError("incast-ecn: DCTCP marking law never "
                             "fired (marks == 0)")
    p99_drop = drop["fabric"].get("fct", {}).get("p99_ns", 0)
    p99_ecn = ecn["fabric"].get("fct", {}).get("p99_ns", 0)
    out = {
        "fan_in": 32,
        "nbytes": 100_000,
        "wall_s": round(drop["wall_s"] + ecn["wall_s"], 3),
        "marks": marks,
        "mark_causes": ecn["fabric"].get("marks", {}),
        "fct_p99_ns_dctcp": p99_ecn,
        "fct_p99_ns_drop_based": p99_drop,
        "peak_queue_dctcp": ecn["fabric"]["peak_queue_depth"],
        "peak_queue_drop_based": drop["fabric"]["peak_queue_depth"],
        "fabric": ecn["fabric"],
    }
    if p99_drop and p99_ecn:
        out["p99_speedup"] = round(p99_drop / p99_ecn, 3)
        print(f"bench[incast-ecn-32]: fct p99 "
              f"{p99_ecn / 1e6:.0f} ms dctcp vs "
              f"{p99_drop / 1e6:.0f} ms drop-based "
              f"({out['p99_speedup']}x), peak queue "
              f"{out['peak_queue_dctcp']} vs "
              f"{out['peak_queue_drop_based']}, marks {marks}",
              file=sys.stderr)
    return out


def sweep_incast_rung() -> dict | None:
    """Standing sweep-fleet rung (ISSUE 12): a small incast campaign
    (fan-in x offered load x cc) run through the full subsystem —
    subprocess points, byte-stable dataset, tail curves — then the
    surrogate trained on the SMALL fan-ins and evaluated on the
    held-out fan-in 16 fabric.  REFUSES to record on dataset-identity
    failure (one point re-run must byte-match its first run) or any
    conservation failure (the aggregator raises) — the numbers below
    exist only behind those gates.  Errors are recorded honestly,
    large or not."""
    import shutil
    import tempfile

    from shadow_tpu.sweep import dataset, runner
    from shadow_tpu.sweep import spec as spec_mod
    from shadow_tpu.surrogate import features as feat_mod
    from shadow_tpu.surrogate import train as train_mod

    spec = {
        "name": "sweep-incast", "scenario": "incast",
        "base": {"nbytes": 100_000, "stop_time": "2s"},
        "axes": {"fan_in": [4, 8, 16], "load": [0.5, 1.0],
                 "cc": ["reno", "dctcp"]},
        "time_limit_s": 300,
        # 1 ms link-sample grid: the per-link queue series thins ~10x
        # with no effect on determinism (the grid rule is
        # path-independent) — the dataset stays MBs, not tens of.
        "link_interval_ms": 1,
    }
    td = tempfile.mkdtemp(prefix="bench-sweep")
    try:
        t0 = time.perf_counter()
        runner.run_campaign(spec, td)
        ds = dataset.aggregate(spec, td)  # conservation gate inside
        campaign_wall = time.perf_counter() - t0

        # Dataset-identity gate: re-run the first point into a fresh
        # directory and byte-compare its fabric channel.  The task
        # dict comes from the SAME recipe the campaign used
        # (runner.point_task), so the gate always compares
        # identically-configured runs.
        p0 = spec_mod.expand(spec)[0]
        td2 = os.path.join(td, "identity-rerun")
        os.makedirs(os.path.join(td2, p0["point_id"]), exist_ok=True)
        runner._run_sub(
            runner.point_task(spec, p0,
                              os.path.join(td2, p0["point_id"])),
            os.path.join(td2, "task.json"),
            os.path.join(td2, "log.txt"), spec["time_limit_s"])
        a = open(os.path.join(td, p0["point_id"],
                              "fabric-sim.bin"), "rb").read()
        b = open(os.path.join(td2, p0["point_id"],
                              "fabric-sim.bin"), "rb").read()
        if a != b:
            raise AssertionError(
                "sweep-incast: point re-run produced different "
                "fabric bytes — dataset identity broken, refusing "
                "to record")

        # Surrogate: train on fan-in {4, 8}, evaluate on the held-out
        # fan-in 16 fabrics (never trained on).
        samples = feat_mod.build_samples(ds)
        tr, held = train_mod.split_samples(samples, "fan_in", 16)
        t0 = time.perf_counter()
        params, hist = train_mod.train(tr, seed=1, steps=250)
        train_wall = time.perf_counter() - t0
        table = train_mod.error_table(params, held)
        print(f"bench[sweep-incast]: {len(samples)} points "
              f"({campaign_wall:.1f}s campaign), surrogate loss "
              f"{hist[0]:.3f}->{hist[-1]:.3f} ({train_wall:.1f}s), "
              f"held-out fan-in 16 rel err p50/p99/p999 "
              f"{table['mean_rel_err_p50']:.1%}/"
              f"{table['mean_rel_err_p99']:.1%}/"
              f"{table['mean_rel_err_p999']:.1%}, identity ok",
              file=sys.stderr)
        return {
            "points": len(samples),
            "campaign_wall_s": round(campaign_wall, 1),
            "train_wall_s": round(train_wall, 1),
            "dataset_bytes": len(ds.to_bytes()),
            "tail_curves": ds.meta["tail_curves"],
            "surrogate_loss_first": round(hist[0], 4),
            "surrogate_loss_last": round(hist[-1], 4),
            "surrogate_error_table": table,
            "held_out": "fan_in>=16",
            "identity": "ok",
        }
    finally:
        shutil.rmtree(td, ignore_errors=True)


def resume_10k_rung() -> dict | None:
    """Standing checkpoint/resume rung (ISSUE 9, docs/CHECKPOINT.md):
    snapshot the 10k Tor-class tgen rung mid-run (5 of 10 sim-s),
    resume it, and byte-compare the determinism-gated artifacts of the
    resumed run against the straight run — REFUSING to record numbers
    if the gate fails.  Records snapshot-write wall, archive size,
    restore (resume-to-first-round) wall, and the wall seconds the
    warm start saves vs re-paying the ramp."""
    import json as _json
    import re
    import shutil
    import tempfile

    from shadow_tpu.core.config import CheckpointConfig
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.ckpt.restore import resume_manager

    td = tempfile.mkdtemp(prefix="bench-resume10k-")

    def build(sub, snapdir):
        cfg = config_10k("tpu", data_dir=os.path.join(td, sub))
        cfg.checkpoint = CheckpointConfig(
            at_ns=[SIM_SECONDS_10K * 1_000_000_000 // 2],
            directory=os.path.join(td, snapdir))
        return cfg

    def gated(data_dir):
        out = {}
        for fn in ("packet-trace.txt", "sim-stats.json"):
            with open(os.path.join(data_dir, fn), "rb") as f:
                data = f.read()
            if fn == "sim-stats.json":
                stats = _json.loads(data)
                stats.get("metrics", {}).pop("wall", None)
                data = _json.dumps(stats, sort_keys=True).encode()
                data = re.sub(rb'"directory": "[^"]*"', b'"<n>"', data)
            out[fn] = data
        return out

    try:
        mgr = Manager(build("straight", "snaps"))
        if mgr.plane is None:
            print("bench[resume-10k]: skipped (no native engine)",
                  file=sys.stderr)
            return None
        t0 = time.perf_counter()
        s = mgr.run()
        straight_wall = time.perf_counter() - t0
        if not s.ok:
            raise RuntimeError(f"straight run failed: "
                               f"{s.plugin_errors[:2]}")
        mgr.write_data_dir(s)
        snap = mgr.ckpt_last_path
        snap_wall = mgr.ckpt_write_wall_s
        snap_bytes = os.path.getsize(snap)

        t0 = time.perf_counter()
        mgr2 = resume_manager(build("resumed", "snaps2"), snap)
        restore_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        s2 = mgr2.run()
        resume_run_wall = time.perf_counter() - t0
        if not s2.ok:
            raise RuntimeError(f"resumed run failed: "
                               f"{s2.plugin_errors[:2]}")
        mgr2.write_data_dir(s2)

        a = gated(os.path.join(td, "straight"))
        b = gated(os.path.join(td, "resumed"))
        bad = [fn for fn in a if a[fn] != b[fn]]
        if bad:
            # The whole point of the rung: never record perf numbers
            # for a resume that is not byte-identical.
            raise RuntimeError(f"byte-identity gate FAILED on {bad} — "
                               f"refusing to record")
        # Honest ramp accounting: the straight run paid the snapshot
        # write too, so the warm start saves (sim wall of the first
        # half) minus (restore + remainder) — negative when the
        # remaining workload is smaller than the restore cost, which
        # is exactly what an operator needs to know.
        sim_wall = straight_wall - snap_wall
        ramp_saved = sim_wall - (restore_wall + resume_run_wall)
        print(f"bench[resume-10k]: snapshot {snap_bytes / 1e6:.1f} MB "
              f"in {snap_wall:.2f}s at sim {SIM_SECONDS_10K / 2:.0f}s; "
              f"restore {restore_wall:.2f}s + remainder "
              f"{resume_run_wall:.1f}s vs straight {sim_wall:.1f}s "
              f"sim wall (warm start saves {ramp_saved:.1f}s); "
              f"byte-identity gate ok", file=sys.stderr)
        return {
            "snapshot_write_wall_s": round(snap_wall, 3),
            "snapshot_bytes": snap_bytes,
            "restore_wall_s": round(restore_wall, 3),
            "resumed_run_wall_s": round(resume_run_wall, 3),
            "straight_run_wall_s": round(sim_wall, 3),
            "ramp_saved_wall_s": round(ramp_saved, 3),
            "byte_identity": "ok",
        }
    finally:
        shutil.rmtree(td, ignore_errors=True)


def scale_100k_rung() -> dict | None:
    """Standing >=100k-host scale rung (engine path): 100k PHOLD LPs
    with ring peer lists stepped through C++ multi-round spans — the
    round-4 prose scale claims as a recorded number (VERDICT r5 weak
    #6).  Returns the JSON fragment for the headline record."""
    from shadow_tpu.core.config import ConfigOptions
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.tools.netgen import phold_args

    # Hosts as a dict (not YAML text): parsing a ~100k-block YAML doc
    # costs minutes; the peer law and arg layout still come from the
    # shared netgen builder.
    n = 100_000
    names = [f"lp{i:06d}" for i in range(n)]
    hosts = {}
    for i, name in enumerate(names):
        hosts[name] = {"network_node_id": 0, "processes": [{
            "path": "phold",
            "args": phold_args(i, names, 1, 20_000_000,
                               peers_per_host=8),
            "start_time": "100ms",
            "expected_final_state": "running"}]}
    cfg = ConfigOptions.from_dict({
        "general": {"stop_time": "0.3s", "seed": 13},
        "network": {"graph": {"type": "gml", "inline": """
graph [ node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "5 ms" ] ]"""}},
        "experimental": {"scheduler": "tpu",
                         "tpu_device_spans": "off"},
        "hosts": hosts})
    t0 = time.perf_counter()
    manager = Manager(cfg)
    build_s = time.perf_counter() - t0
    for h in manager.hosts:
        h.set_tracing(False)
    t0 = time.perf_counter()
    summary = manager.run()
    wall = time.perf_counter() - t0
    events_s = summary.events / wall if wall > 0 else 0.0
    cov = 100.0 * summary.span_rounds / max(summary.rounds, 1)
    print(f"bench[scale-100k]: {n} hosts, {summary.events} events, "
          f"{summary.packets_sent} messages in {wall:.1f}s "
          f"({events_s:,.0f} events/s, build {build_s:.1f}s, span "
          f"coverage {cov:.0f}%)", file=sys.stderr)
    return {"hosts": n, "events": summary.events,
            "wall_s": round(wall, 2),
            "events_per_s": round(events_s),
            "span_coverage_pct": round(cov, 1)}


def mixed_pcap_rung() -> None:
    """10k rung variant with a handful of pcap'd OBJECT-PATH hosts
    (per-host native_dataplane off): the all-plane span cliff is
    lifted — spans cap at the earliest object-host window and
    engine->object packets ride the span-export path — so coverage
    must stay >=50% with counts identical to the engine baseline."""
    import tempfile

    def extra():
        # four short-lived pcap'd clients: one 10 KB transfer each,
        # finished within the first sim-second of a 3 s window
        out = {}
        for i in range(4):
            out[f"pcap{i:02d}"] = {
                "network_node_id": 1,
                "pcap_enabled": True,
                "native_dataplane": False,
                "processes": [{
                    "path": "tgen-client",
                    "args": [f"relay{i:04d}", "80", "10000", "1"],
                    "start_time": f"{150 + i * 20}ms",
                    "expected_final_state": "any",
                }],
            }
        return out

    with tempfile.TemporaryDirectory() as td:
        sE, _wE = run_once(
            lambda s_: config_10k(s_, stop_s=3, extra_hosts=extra(),
                                  data_dir=os.path.join(td, "e"),
                                  native_dataplane="on"),
            "thread_per_core")
        sT, wall = run_once(
            lambda s_: config_10k(s_, stop_s=3, extra_hosts=extra(),
                                  data_dir=os.path.join(td, "t")),
            "tpu")
    assert sT.packets_sent == sE.packets_sent, \
        (sT.packets_sent, sE.packets_sent)
    cov = 100.0 * sT.span_rounds / max(sT.rounds, 1)
    print(f"bench[10k-mixed-pcap]: 10k engine hosts + 4 pcap'd "
          f"object-path hosts, {sT.packets_sent} packets in "
          f"{wall:.1f}s; span coverage {sT.span_rounds}/{sT.rounds} "
          f"rounds ({cov:.0f}%), counts == engine baseline",
          file=sys.stderr)
    assert cov >= 50.0, f"span coverage {cov:.0f}% < 50%"


def lint_preflight() -> None:
    """One-line lint gate, all four analysis passes: a benchmark
    artifact recorded from a tree with twin drift would compare a C++
    engine against a Python kernel that no longer computes the same
    thing, and one recorded with an epoch/ownership/knob violation
    (pass 4) could be measuring stale-residency reuse.  The preflight
    wall is reported so the passes provably stay under the lint
    budget (<30 s, tests/test_twin_contract.py)."""
    import time
    from shadow_tpu.analysis import run_all
    t0 = time.perf_counter()  # shadow-lint: allow[wall-clock] preflight timing
    violations, counts = run_all(
        os.path.dirname(os.path.abspath(__file__)))
    dt = time.perf_counter() - t0  # shadow-lint: allow[wall-clock] preflight timing
    if violations:
        print(f"lint: FAIL ({len(violations)} violation(s); "
              f"run scripts/lint)", file=sys.stderr)
        for v in violations[:10]:
            print(f"  {v.render()}", file=sys.stderr)
        sys.exit(1)
    print(f"lint: ok ({', '.join(counts)} in {dt:.2f}s)",
          file=sys.stderr)


def main() -> None:
    lint_preflight()
    # Persistent XLA compile cache: the device-span kernels (PHOLD and
    # especially the TCP family's multi-round while_loop) cost minutes
    # of compile on the CPU backend; repeated bench runs must not pay
    # it every time.  Harmless on accelerators (same mechanism).
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.expanduser("~/.cache/shadow_tpu_xla"))
    if not tpu_available():
        # 8 virtual CPU devices so the sharded rung below can run even
        # when the accelerator is down (must be set before the first
        # backend init in this process).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = \
                (flags + " --xla_force_host_platform_device_count=8").strip()
        from shadow_tpu.utils.platform import force_cpu
        force_cpu()
        print("bench: accelerator unavailable; kernel on CPU backend",
              file=sys.stderr)

    # Secondary: the 100-host UDP mesh where propagation dominates.
    mesh_base, mesh_base_wall = run_best(mesh_config, "thread_per_core")
    run_once(mesh_config, "tpu")  # warmup: compiles the batch buckets
    mesh_tpu, mesh_tpu_wall = run_best(mesh_config, "tpu")
    print(f"bench[mesh-100]: tpu "
          f"{mesh_tpu.packets_sent / mesh_tpu_wall:.0f} pkts/s, "
          f"thread_per_core "
          f"{mesh_base.packets_sent / mesh_base_wall:.0f} pkts/s, "
          f"ratio {mesh_base_wall / mesh_tpu_wall:.3f}", file=sys.stderr)

    # Secondary: the 1k-host 3-tier config (round-2's headline).
    base1k, base1k_wall = run_best(config3, "thread_per_core")
    run_once(config3, "tpu")  # warmup: JIT-compiles the batch buckets
    tpu1k, tpu1k_wall = run_best(config3, "tpu")
    assert tpu1k.packets_sent == base1k.packets_sent, \
        "schedulers disagreed on 1k workload size"
    print(f"bench[3tier-1k]: {tpu1k.packets_sent} packets, tpu "
          f"{tpu1k.busy_end_ns / 1e9 / tpu1k_wall:.2f} sim-s/wall-s "
          f"({tpu1k_wall:.1f}s wall), thread_per_core "
          f"{base1k.busy_end_ns / 1e9 / base1k_wall:.2f} "
          f"({base1k_wall:.1f}s wall), ratio "
          f"{base1k_wall / tpu1k_wall:.3f}", file=sys.stderr)

    # Headline: the 10k-host Tor-class ladder rung (BASELINE config 4).
    # TWO baselines (VERDICT r3): the reference-faithful pure-Python
    # thread_per_core (GIL-bound — overstates the win), and the HONEST
    # engine-backed thread_per_core (real OS threads over C++ engine
    # hosts, no GIL in the hot loop) — the recorded vs_baseline.
    # thread_per_core at this scale runs once (minutes); the tpu run is
    # best-of-two after the 1k warmup primed the kernels.
    base_summary, base_wall = run_once(config_10k, "thread_per_core")
    # The engine baseline and the tpu run get SYMMETRIC treatment:
    # interleaved trials (E,T,E,T,E,T), best wall on each side.  A
    # single-trial baseline vs best-of-N challenger — or back-to-back
    # blocks on a shared box with ±10% drift — would let noise and
    # run order decide the recorded ratio.
    buildE = lambda s: config_10k(s, native_dataplane="on")  # noqa: E731
    baseE_summary = baseE_wall = None
    tpu_summary = tpu_wall = None
    tpu_walls = []
    baseE_walls = []
    for trial in range(3):
        sE, wE = run_once(buildE, "thread_per_core")
        baseE_walls.append(wE)
        if baseE_wall is None or wE < baseE_wall:
            baseE_summary, baseE_wall = sE, wE
        sT, wT = run_once(config_10k, "tpu",
                          report_routes="10k" if trial == 2 else None)
        tpu_walls.append(wT)
        if tpu_wall is None or wT < tpu_wall:
            tpu_summary, tpu_wall = sT, wT
    # Phase breakdown + eligibility histogram of the last recorded tpu
    # trial (flight recorder wall channel; ISSUE 4) — one line each in
    # the lint-preflight style, and recorded in the headline JSON.
    tpu_obs = dict(LAST_RUN)
    phases = tpu_obs.get("phases_s", {})
    print("phases: " + (" | ".join(
        f"{k} {v}s" for k, v in sorted(phases.items(),
                                       key=lambda kv: -kv[1]))
        or "n/a"), file=sys.stderr)
    elig = tpu_obs.get("eligibility", {})
    etot = sum(elig.values()) or 1
    print("eligibility: " + (", ".join(
        f"{k} {v} ({100.0 * v / etot:.0f}%)"
        for k, v in sorted(elig.items(), key=lambda kv: -kv[1]))
        or "n/a"), file=sys.stderr)
    # Device-capability probe on a SEPARATE, non-recorded run: the
    # per-round domain scan costs ~1% at 10k hosts and must not taint
    # any trial that feeds the recorded walls/spread.
    run_once(config_10k, "tpu", report_routes="10k-devcap",
             devcap=True)
    assert baseE_summary.packets_sent == base_summary.packets_sent, \
        "engine baseline disagreed on workload size"
    print(f"bench[10k-baselines]: thread_per_core python "
          f"{base_summary.busy_end_ns / 1e9 / base_wall:.3f} sim-s/wall-s "
          f"({base_wall:.1f}s), thread_per_core engine "
          f"{baseE_summary.busy_end_ns / 1e9 / baseE_wall:.3f} sim-s/wall-s "
          f"({baseE_wall:.1f}s)", file=sys.stderr)

    # Forced-device audit rung: every propagation round through the
    # jitted device kernel (tpu_min_device_batch=0), short window — on
    # a tunnelled chip each dispatch pays a full round trip, and this
    # number shows what the accelerator itself delivers vs the cost
    # model's blended route above.  0.15 sim-s ≈ 100+ dispatches: a
    # statistically solid per-dispatch sample without taxing the bench
    # budget (2 sim-s through a tunnel was ~15 min of wall).
    fd_summary, fd_wall = run_once(
        lambda s: config_10k(s, stop_s="0.15", tpu_min_device_batch=0),
        "tpu", report_routes="10k-forced-device")
    print(f"bench[10k-forced-device]: {fd_summary.packets_sent} packets "
          f"in {fd_wall:.1f}s wall over {fd_summary.busy_end_ns / 1e9:.2f} "
          f"sim-s = {fd_summary.busy_end_ns / 1e9 / fd_wall:.3f} "
          f"sim-s/wall-s (0.15 sim-s window)", file=sys.stderr)

    assert tpu_summary.packets_sent == base_summary.packets_sent, \
        "schedulers disagreed on workload size"
    assert tpu_summary.busy_end_ns == base_summary.busy_end_ns, \
        "schedulers disagreed on busy span"

    # Standing >=100k-host engine-path rung, recorded in the headline
    # JSON (engine-only: no device/tunnel risk ahead of the print).
    try:
        scale_100k = scale_100k_rung()
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[scale-100k]: failed: {e}", file=sys.stderr)
        scale_100k = None

    # Incast fan-in smoke with the fabric conservation gate (ISSUE 8),
    # recorded in the headline JSON (engine path, no tunnel risk).
    try:
        incast = incast_rung()
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[incast-32]: failed: {e}", file=sys.stderr)
        incast = None

    # DCTCP incast rung (ISSUE 10): the same fan-in under
    # `tcp: {cc: dctcp, ecn: on}` — marks must fire, conservation
    # must hold, FCT p99 recorded next to the drop-based figure.
    try:
        incast_ecn = incast_ecn_rung()
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[incast-ecn-32]: failed: {e}", file=sys.stderr)
        incast_ecn = None

    # Sweep fleet + surrogate rung (ISSUE 12): a small incast
    # campaign through the whole subsystem — identity-gated dataset,
    # tail curves, surrogate error table on the held-out fan-in.
    try:
        sweep_incast = sweep_incast_rung()
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[sweep-incast]: failed: {e}", file=sys.stderr)
        sweep_incast = None

    # Checkpoint/resume rung (ISSUE 9): snapshot the 10k rung mid-run,
    # resume, byte-compare — numbers recorded only when the identity
    # gate holds (engine path, no tunnel risk).
    try:
        resume_10k = resume_10k_rung()
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[resume-10k]: failed: {e}", file=sys.stderr)
        resume_10k = None

    # Device-span crossover ladder (ISSUE 15): the shape-pinned
    # 1k-ring/8k/64k + mesh-dev rungs with the device-kernel
    # observatory on — per-stage occupancy and attributed
    # us/host/round recorded next to the fitted slope in the headline
    # JSON.  A rung whose kernel channel fails the
    # fires-vs-micro_iters conservation check refuses to record and
    # fails the exit code below.
    try:
        phold_ladder = phold_rung()
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[phold-ladder]: failed: {e}", file=sys.stderr)
        phold_ladder = None

    # Sharded rungs (ISSUE 11): the 1/2/4/8 shard-count scaling curve
    # for the 10k rung, the STANDING sharded 100k rung, the leaf-spine
    # rack rung and the 1M-host stretch — each in its own subprocess
    # on a virtual 8-device mesh, each identity-gated (a sharded rung
    # that cannot prove trace byte-identity refuses to record).
    sharded_10k = sharded_fragment("--sharded-10k", 5400)
    scale_100k_sharded = sharded_fragment("--sharded-100k", 3000)
    leaf_spine_sharded = sharded_fragment("--sharded-leafspine", 1800)
    stretch_1m = sharded_fragment("--sharded-1m", 3000)

    # Managed-process emulator rung (real binaries under the shim) —
    # recorded in the headline JSON with syscalls_per_sec, the SC_*
    # disposition histogram and the IPC wall breakdown (ISSUE 7 /
    # ROADMAP item 2's acceptance metric).  No device/tunnel risk:
    # safe ahead of the print.
    managed_failed = False
    try:
        managed_128 = managed_rung()
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[managed-128]: failed: {e}", file=sys.stderr)
        managed_128 = None
        managed_failed = True

    # Managed scale-out rungs (ISSUE 13 / ROADMAP item 2): the
    # STANDING 1k-process rung (failure fails the bench exit code)
    # and the 10k stretch whose outcome — fd exhaustion, spawn storm,
    # timeout — is recorded honestly like the 1M-host stretch.
    try:
        managed_1k = managed_scale_rung(1000, "managed-1k")
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[managed-1k]: failed: {e}", file=sys.stderr)
        managed_1k = None
        managed_failed = True
    managed_10k = managed_scale_rung(10_000, "managed-10k",
                                     record_outcome=True)

    # Chaos rung (docs/ROBUSTNESS.md): injected crash+hang during a
    # managed run — refuses to record unless the ledger replay is
    # byte-identical and drop-cause conservation is exact.  A refusal
    # fails the bench exit code like the standing managed rungs.
    try:
        chaos_128 = chaos_managed_rung()
        if chaos_128 is not None and not chaos_128.get("ok"):
            managed_failed = True
    except Exception as e:  # noqa: BLE001 — never cost the headline
        print(f"bench[chaos-managed-128]: failed: {e}",
              file=sys.stderr)
        chaos_128 = None
        managed_failed = True

    # The event-driven loop stops touching hosts once events drain; the
    # metric credits only the span that actually ran rounds (an idle
    # tail up to stop_time is free for every scheduler).
    sim_seconds = tpu_summary.busy_end_ns / 1e9
    sim_per_wall = sim_seconds / tpu_wall
    print(f"bench[10k]: {tpu_summary.packets_sent} packets, tpu "
          f"{tpu_summary.packets_sent / tpu_wall:.0f} pkts/s "
          f"({tpu_wall:.1f}s wall), ratio vs python thread_per_core "
          f"{base_wall / tpu_wall:.2f}x, vs ENGINE thread_per_core "
          f"{baseE_wall / tpu_wall:.2f}x", file=sys.stderr)

    # The headline JSON prints BEFORE the auxiliary rungs: a tunnel
    # stall inside an optional rung must not cost the recorded result
    # (the driver reads stdout's JSON; rungs write stderr only).
    def spread(walls):
        ws = sorted(walls)
        return {"min_s": round(ws[0], 3),
                "median_s": round(ws[len(ws) // 2], 3),
                "max_s": round(ws[-1], 3)}

    print(json.dumps({
        "metric": f"sim-seconds/wallclock-sec, {HOSTS_10K}-host Tor-class "
                  f"tgen TCP (scheduler=tpu vs engine-backed "
                  f"thread_per_core; python-baseline ratio "
                  f"{round(base_wall / tpu_wall, 2)}x on stderr)",
        "value": round(sim_per_wall, 3),
        "unit": "sim-s/wall-s",
        "vs_baseline": round(baseE_wall / tpu_wall, 3),
        # Cold-start wall (first tpu trial: cold caches, any in-window
        # compile/probe cost) recorded alongside the warm best-of-N —
        # cold start is real user experience, not just narration.
        "cold_wall_s": round(tpu_walls[0], 3),
        "warm_wall_s": round(tpu_wall, 3),
        # Full >=3-trial spread for BOTH sides of the headline ratio
        # (VERDICT r5 weak #3): the recorded margin is ~6%, which a
        # single interleaved pair cannot reproduce from the artifact.
        "tpu_trials": spread(tpu_walls),
        "engine_baseline_trials": spread(baseE_walls),
        # Standing scale rung: >=100k hosts on the engine span path.
        "scale_100k": scale_100k,
        # Sharded rungs (ISSUE 11), all identity-gated: the 10k
        # shard-count scaling curve (1/2/4/8 virtual devices — spans
        # are the default routed path for tpu_shards > 1, so the
        # 8-shard figure no longer pays a per-round host shuffle),
        # the standing sharded 100k rung with trace byte-identity vs
        # the engine baseline asserted, the leaf-spine ECMP rack rung
        # on the sharded path, and the 1M-host stretch with its
        # outcome recorded honestly.
        "sharded_10k": sharded_10k,
        "scale_100k_sharded": scale_100k_sharded,
        "leaf_spine_sharded": leaf_spine_sharded,
        "stretch_1m": stretch_1m,
        # Managed-process emulator rung: 128 real binaries under the
        # shim with syscalls/sec, the syscall-observatory disposition
        # histogram (always-on counters) and the IPC round-trip wall
        # breakdown from the wall-profiled companion run (ISSUE 7).
        "managed_128": managed_128,
        # Managed scale-out (ISSUE 13): the standing 1k-process rung
        # (sim-s/wall-s + syscalls_per_sec under the syscall service
        # plane) and the 10k stretch with its outcome recorded
        # honestly.
        "managed_1k": managed_1k,
        "managed_10k": managed_10k,
        "chaos_managed_128": chaos_128,
        # Flight-recorder wall channel of the last recorded tpu trial:
        # where a dispatch's wall goes (export/convert/compile/execute/
        # import/barrier/host-loop/engine-span, seconds) and the
        # device-eligibility histogram (one reason per round).
        "phases_s": phases,
        "eligibility": elig,
        # Sim-netstat (ISSUE 5): per-cause drop counts of the last
        # recorded tpu trial (conservation-checked: wire causes sum
        # to packets_dropped) and the TCP retransmit-rate figure.
        "drops": tpu_obs.get("drops", {}),
        "retransmit_rate": tpu_obs.get("retransmit_rate", 0.0),
        # Fabric observatory (ISSUE 8): peak queue depth, hottest-link
        # utilization, refill stalls and FCT percentiles of the last
        # recorded tpu trial (always-on counters), plus the incast
        # fan-in rung with its conservation gate.
        "fabric": tpu_obs.get("fabric", {}),
        "incast": incast,
        # DCTCP/ECN (ISSUE 10): the incast fan-in re-run under
        # cc=dctcp — nonzero marks, exact conservation, and the FCT
        # p99 next to the drop-based rung's.
        "incast_ecn": incast_ecn,
        # Sweep fleet + learned surrogate (ISSUE 12): tail curves
        # (p50/p99/p999 vs offered load per fan-in x cc) and the
        # surrogate-vs-simulator per-quantile error table on the
        # held-out fan-in 16 fabric — recorded ONLY behind the
        # dataset-identity and conservation gates.
        "sweep_incast": sweep_incast,
        # Checkpoint/resume (ISSUE 9): snapshot size + write wall,
        # restore wall and the wall saved by warm-starting past the
        # 10k rung's first half — recorded ONLY when the resumed run
        # is byte-identical to the straight run.
        "resume_10k": resume_10k,
        # Device-kernel observatory (ISSUE 15): the crossover ladder
        # with per-stage occupancy + attributed us/host/round per
        # rung, the fitted slopes, and the attribution of the
        # largest fit rung next to them — conservation-gated.
        "phold_ladder": phold_ladder,
    }), flush=True)

    # Auxiliary rungs (stderr only).  A failure must not cost the
    # already-printed headline JSON, but it must still fail the bench
    # exit code so automation sees rung regressions.
    failed = ["managed_rung"] if managed_failed else []

    def sharded_bad(frag):
        # Identity refusals and subprocess failures fail the bench
        # exit code (the headline JSON already printed the honest
        # nulls/outcomes).  The 1M stretch is exempt: its outcome —
        # including a failure mode — IS the record.
        if frag is None:
            return True
        if str(frag.get("identity", "ok")).startswith("FAILED"):
            return True
        out = str(frag.get("outcome", ""))
        return out.startswith("timeout") or out.startswith("failed")

    for name, frag in (("sharded_10k", sharded_10k),
                       ("scale_100k_sharded", scale_100k_sharded),
                       ("leaf_spine_sharded", leaf_spine_sharded)):
        if sharded_bad(frag):
            failed.append(name)
    # The crossover ladder now records in the headline JSON (ISSUE
    # 15); a kernel-channel conservation refusal fails the exit code
    # like the sharded identity gates.
    if phold_ladder is None or phold_ladder.get("refused"):
        failed.append("phold_ladder")
    for rung in (mixed_pcap_rung,  # ISSUE 3: all-plane cliff lifted
                 tcp_dev_rung):   # ISSUE 1: TCP device-span family
        # (managed_rung moved ahead of the headline JSON — its
        # syscalls_per_sec/disposition/IPC numbers are recorded there.)
        try:
            rung()
        except Exception as e:  # noqa: BLE001 — isolate, then report
            failed.append(rung.__name__)
            print(f"bench[{rung.__name__}]: failed: {e}",
                  file=sys.stderr)
    if failed:
        sys.exit(f"bench: auxiliary rungs failed: {', '.join(failed)}")


_SHARDED_ENTRIES = {
    "--sharded-10k": sharded_curve_main,
    "--sharded-100k": sharded_100k_main,
    "--sharded-leafspine": sharded_leaf_spine_main,
    "--sharded-1m": sharded_1m_main,
}

if __name__ == "__main__":
    entry = next((fn for flag, fn in _SHARDED_ENTRIES.items()
                  if flag in sys.argv), None)
    if entry is not None:
        from shadow_tpu.utils.platform import honor_platform_env
        honor_platform_env()
        entry()
    else:
        main()
